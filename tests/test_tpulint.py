"""tpulint gate: both static-analysis layers run tier-1, CPU-only.

Unit tests pin each AST rule's fire/no-fire behavior on synthetic
snippets; the repo-level tests are the actual gate — the working tree must
be clean against the committed baseline, and every jaxpr invariant must
hold on the real traced programs.  The x64-drift tests cover the two ways
a float64 has historically crept into JAX training states (host-side
init, checkpoint import).
"""

import os

import numpy as np
import pytest

from mx_rcnn_tpu.analysis import (
    build_programs,
    lint_paths,
    lint_source,
    load_baseline,
    new_findings,
    run_jaxpr_checks,
    write_baseline,
)
from mx_rcnn_tpu.analysis.jaxpr_checks import ALL_CHECKS

pytestmark = pytest.mark.tpulint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO_ROOT, "tpulint_baseline.json")
# Any path under a traced prefix works for snippet tests.
TRACED = "mx_rcnn_tpu/detection/_snippet.py"

HEADER = "import numpy as np\nimport jax\nimport jax.numpy as jnp\nfrom jax import lax\n"


def rules_of(src, path=TRACED):
    return [f.rule for f in lint_source(HEADER + src, path)]


# ---------------------------------------------------------------------------
# Layer 1: AST rules


class TestAstRules:
    def test_host_cast_on_value_fires(self):
        assert rules_of("def f(x):\n    return float(x)\n") == ["TPU001"]

    def test_cast_of_literal_exempt(self):
        assert rules_of("SCALE = float(16 * 2)\nN = int(-3)\n") == []

    def test_item_and_tolist_fire(self):
        src = "def f(x):\n    a = x.item()\n    b = x.tolist()\n    return a, b\n"
        assert rules_of(src) == ["TPU001", "TPU001"]

    def test_np_asarray_fires_as_host_cast(self):
        assert rules_of("def f(x):\n    return np.asarray(x)\n") == ["TPU001"]

    def test_np_computation_fires(self):
        assert rules_of("def f(x):\n    return np.sqrt(x)\n") == ["TPU002"]

    def test_np_dtype_attr_exempt(self):
        assert rules_of("def f(x):\n    return x.astype(np.float32)\n") == []

    def test_branch_on_jnp_expression_fires(self):
        src = "def f(x):\n    if jnp.any(x > 0):\n        return x\n    return -x\n"
        assert rules_of(src) == ["TPU003"]

    def test_branch_on_python_value_exempt(self):
        assert rules_of("def f(x, n):\n    if n > 0:\n        return x\n    return -x\n") == []

    def test_unsorted_dict_iteration_fires(self):
        src = "def f(d):\n    return [v for k, v in d.items()]\n"
        assert rules_of(src) == ["TPU004"]

    def test_sorted_dict_iteration_exempt(self):
        src = "def f(d):\n    return [v for k, v in sorted(d.items())]\n"
        assert rules_of(src) == []

    def test_unscoped_mxu_op_fires(self):
        assert rules_of("def f(a, b):\n    return jnp.dot(a, b)\n") == ["TPU005"]

    def test_named_scope_exempts_mxu_op(self):
        src = (
            "def f(a, b):\n"
            "    with jax.named_scope('proj'):\n"
            "        return jnp.dot(a, b)\n"
        )
        assert rules_of(src) == []

    def test_flax_module_exempts_mxu_op(self):
        src = (
            "from flax import linen as nn\n"
            "class Proj(nn.Module):\n"
            "    def __call__(self, a, b):\n"
            "        return a @ b\n"
        )
        assert rules_of(src) == []

    def test_matmul_operator_fires(self):
        assert rules_of("def f(a, b):\n    return a @ b\n") == ["TPU005"]

    def test_non_traced_path_is_exempt(self):
        src = "def f(x):\n    return float(np.sqrt(x))\n"
        assert lint_source(HEADER + src, "mx_rcnn_tpu/data/loader.py") == []

    def test_obs_import_fires(self):
        assert rules_of("import mx_rcnn_tpu.obs\n") == ["TPU007"]

    def test_obs_from_import_fires(self):
        assert rules_of("from mx_rcnn_tpu.obs import journal\n") == ["TPU007"]

    def test_obs_submodule_import_fires(self):
        assert rules_of("from mx_rcnn_tpu.obs.metrics import Counter\n") == ["TPU007"]

    def test_obs_attr_import_fires(self):
        assert rules_of("from mx_rcnn_tpu import obs\n") == ["TPU007"]

    def test_obs_sibling_import_exempt(self):
        assert rules_of("from mx_rcnn_tpu import config\n") == []

    def test_obs_import_exempt_outside_traced_code(self):
        src = "from mx_rcnn_tpu import obs\n"
        assert lint_source(HEADER + src, "mx_rcnn_tpu/serve/engine.py") == []

    def test_pallas_call_without_interpret_fires(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "def f(x):\n"
            "    return pl.pallas_call(kern, out_shape=sh)(x)\n"
        )
        assert rules_of(src) == ["TPU008"]

    def test_pallas_call_with_interpret_exempt(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "def f(x, interpret=False):\n"
            "    return pl.pallas_call(kern, out_shape=sh, "
            "interpret=interpret)(x)\n"
        )
        assert rules_of(src) == []


# ---------------------------------------------------------------------------
# Baseline ratchet semantics


class TestBaseline:
    def _findings(self, src):
        return lint_source(HEADER + src, TRACED)

    def test_roundtrip_suppresses(self, tmp_path):
        f = self._findings("def f(x):\n    return float(x)\n")
        path = str(tmp_path / "b.json")
        write_baseline(path, f)
        assert new_findings(f, load_baseline(path)) == []

    def test_line_move_stays_suppressed(self, tmp_path):
        path = str(tmp_path / "b.json")
        write_baseline(path, self._findings("def f(x):\n    return float(x)\n"))
        moved = self._findings("# comment\n\ndef f(x):\n    return float(x)\n")
        assert new_findings(moved, load_baseline(path)) == []

    def test_extra_occurrence_is_new(self, tmp_path):
        path = str(tmp_path / "b.json")
        write_baseline(path, self._findings("def f(x):\n    return float(x)\n"))
        doubled = self._findings(
            "def f(x):\n    return float(x)\ndef g(x):\n    return float(x)\n"
        )
        assert len(new_findings(doubled, load_baseline(path))) == 1

    def test_edited_line_is_new(self, tmp_path):
        path = str(tmp_path / "b.json")
        write_baseline(path, self._findings("def f(x):\n    return float(x)\n"))
        edited = self._findings("def f(x):\n    return float(x.sum())\n")
        assert len(new_findings(edited, load_baseline(path))) == 1

    def test_missing_baseline_means_everything_new(self, tmp_path):
        f = self._findings("def f(x):\n    return float(x)\n")
        empty = load_baseline(str(tmp_path / "absent.json"))
        assert len(new_findings(f, empty)) == 1

    def test_version_mismatch_raises(self, tmp_path):
        path = tmp_path / "b.json"
        path.write_text('{"version": 99, "suppressions": {}}')
        with pytest.raises(ValueError, match="version"):
            load_baseline(str(path))


# ---------------------------------------------------------------------------
# Repo-level gate


class TestRepoGate:
    def test_working_tree_clean_against_baseline(self):
        findings = lint_paths(REPO_ROOT)
        new = new_findings(findings, load_baseline(BASELINE))
        assert not new, "new lint findings beyond tpulint_baseline.json:\n" + "\n".join(
            f.format() for f in new
        )

    def test_seeded_violation_is_caught(self):
        path = os.path.join(REPO_ROOT, "mx_rcnn_tpu/detection/graph.py")
        with open(path) as f:
            src = f.read()
        seeded = src + "\n\ndef _seeded(x):\n    return float(x.sum())\n"
        findings = lint_source(seeded, "mx_rcnn_tpu/detection/graph.py")
        new = new_findings(findings, load_baseline(BASELINE))
        assert [f.rule for f in new] == ["TPU001"]


# ---------------------------------------------------------------------------
# Layer 2: jaxpr invariants on the real programs


@pytest.fixture(scope="module")
def programs():
    return build_programs("tiny_synthetic")


@pytest.mark.parametrize("check", ALL_CHECKS, ids=lambda c: c.__name__)
def test_jaxpr_invariant(programs, check):
    r = check(programs)
    assert r.ok, f"{r.name}: {r.detail}"


def test_run_jaxpr_checks_reports_every_check(programs):
    results = run_jaxpr_checks("tiny_synthetic", programs)
    assert [r.name for r in results] == [
        c.__name__.removeprefix("check_") for c in ALL_CHECKS
    ]
    assert all(r.ok for r in results), [
        (r.name, r.detail) for r in results if not r.ok
    ]


# ---------------------------------------------------------------------------
# x64 drift


def _wide_leaves(tree):
    import jax

    return [
        str(np.asarray(leaf).dtype)
        for leaf in jax.tree_util.tree_leaves(tree)
        if str(np.asarray(leaf).dtype) in ("float64", "int64")
    ]


def test_create_train_state_has_no_x64_leaves(programs):
    state = programs.state
    assert _wide_leaves(state.params) == []
    assert _wide_leaves(state.opt_state) == []
    assert _wide_leaves(state.model_state) == []


def _fake_resnet_stem_sd(dtype):
    return {
        "conv1.weight": np.ones((4, 3, 7, 7), dtype),
        "bn1.weight": np.ones((4,), dtype),
        "bn1.bias": np.zeros((4,), dtype),
        "bn1.running_mean": np.zeros((4,), dtype),
        "bn1.running_var": np.ones((4,), dtype),
    }


def test_map_torch_resnet_casts_f64_to_f32():
    from mx_rcnn_tpu.train.import_torch import map_torch_resnet

    params, constants = map_torch_resnet(_fake_resnet_stem_sd(np.float64))
    assert _wide_leaves(params) == []
    assert _wide_leaves(constants) == []
    assert params["conv1"]["kernel"].shape == (7, 7, 3, 4)


def test_load_pretrained_backbone_preserves_model_dtypes(tmp_path):
    torch = pytest.importorskip("torch")
    from mx_rcnn_tpu.train.import_torch import load_pretrained_backbone

    sd = {
        k: torch.from_numpy(v)
        for k, v in _fake_resnet_stem_sd(np.float64).items()
    }
    path = str(tmp_path / "stem.pth")
    torch.save(sd, path)
    variables = {
        "params": {
            "backbone": {"conv1": {"kernel": np.zeros((7, 7, 3, 4), np.float32)}}
        },
        "constants": {
            "backbone": {
                "bn1": {
                    "scale": np.zeros((4,), np.float32),
                    "bias": np.zeros((4,), np.float32),
                    "mean": np.zeros((4,), np.float32),
                    "var": np.ones((4,), np.float32),
                }
            }
        },
    }
    out = load_pretrained_backbone(variables, path)
    assert _wide_leaves(out) == []
    np.testing.assert_array_equal(
        out["params"]["backbone"]["conv1"]["kernel"], 1.0
    )
