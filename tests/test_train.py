"""Unit tests for the training runtime (schedule, masking, state, ckpt)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mx_rcnn_tpu.config import ScheduleConfig, TrainConfig
from mx_rcnn_tpu.train import (
    TrainState,
    latest_step,
    make_optimizer,
    make_schedule,
    restore_checkpoint,
    save_checkpoint,
)
from mx_rcnn_tpu.train.optim import frozen_mask


class TestSchedule:
    def test_warmup_and_decay(self):
        cfg = ScheduleConfig(
            base_lr=0.02, warmup_steps=100, warmup_factor=1 / 3,
            decay_steps=(1000, 2000), factor=0.1, total_steps=3000,
        )
        s = make_schedule(cfg)
        assert np.isclose(float(s(0)), 0.02 / 3)
        assert np.isclose(float(s(100)), 0.02)
        assert np.isclose(float(s(500)), 0.02)
        assert np.isclose(float(s(1500)), 0.002)
        assert np.isclose(float(s(2500)), 0.0002, atol=1e-8)

    def test_linear_scaling(self):
        cfg = ScheduleConfig(base_lr=0.01, warmup_steps=1)
        s = make_schedule(cfg, scale=8.0)
        assert np.isclose(float(s(10)), 0.08)

    def test_scale_schedule_steps(self):
        from mx_rcnn_tpu.train.loop import scale_schedule_steps

        sched = ScheduleConfig(
            decay_steps=(60000, 80000), total_steps=90000, reference_batch=16
        )
        out = scale_schedule_steps(sched, 64)
        assert out.decay_steps == (15000, 20000)
        assert out.total_steps == 22500
        # Identity cases: matching batch, and absolute-steps presets.
        assert scale_schedule_steps(sched, 16) is sched
        absolute = dataclasses.replace(sched, reference_batch=0)
        assert scale_schedule_steps(absolute, 64) is absolute

    @pytest.mark.skipif(
        jax.device_count() < 8, reason="needs the 8-device fake mesh"
    )
    def test_build_all_linear_scaling_rule(self, monkeypatch):
        """VERDICT r2 #6: a 64-global-batch run must train 1/4 the steps at
        4x lr — both halves applied by build_all, visibly to the optimizer."""
        from mx_rcnn_tpu.config import get_config
        from mx_rcnn_tpu.parallel import make_mesh
        from mx_rcnn_tpu.train import loop as L

        captured = {}
        orig = L.make_optimizer

        def spy(train_cfg, params, lr_scale=1.0, **kw):
            captured["sched"] = train_cfg.schedule
            captured["lr_scale"] = lr_scale
            return orig(train_cfg, params, lr_scale=lr_scale, **kw)

        monkeypatch.setattr(L, "make_optimizer", spy)
        cfg = get_config("tiny_synthetic")
        cfg = dataclasses.replace(
            cfg,
            train=dataclasses.replace(
                cfg.train,
                per_device_batch=8,  # 8 fake devices -> global batch 64
                schedule=ScheduleConfig(
                    decay_steps=(60000, 80000), total_steps=90000,
                    reference_batch=16,
                ),
            ),
        )
        *_, gb = L.build_all(cfg, make_mesh())
        assert gb == 64
        assert np.isclose(captured["lr_scale"], 4.0)
        assert captured["sched"].decay_steps == (15000, 20000)
        assert captured["sched"].total_steps == 22500


class TestFrozenMask:
    def test_prefix_freezing(self):
        params = {
            "backbone": {"conv1": {"kernel": jnp.ones(3)}, "res3": {"kernel": jnp.ones(3)}},
            "rpn": {"conv": {"kernel": jnp.ones(3)}},
        }
        mask = frozen_mask(params, ("backbone/conv1",))
        assert mask["backbone"]["conv1"]["kernel"] is False
        assert mask["backbone"]["res3"]["kernel"] is True
        assert mask["rpn"]["conv"]["kernel"] is True

    def test_deep_components_not_matched(self):
        """Freezing the stem's backbone/conv1 must NOT freeze same-named
        modules elsewhere: the bottleneck-internal conv1
        (backbone/layerN_blockM/conv1) or the mask head's conv1."""
        params = {
            "backbone": {
                "conv1": {"kernel": jnp.ones(3)},
                "layer2_block0": {"conv1": {"kernel": jnp.ones(3)}},
            },
            "mask_head": {"conv1": {"kernel": jnp.ones(3)}},
        }
        mask = frozen_mask(
            params, ("backbone/conv1", "backbone/bn1", "backbone/layer1")
        )
        assert mask["backbone"]["conv1"]["kernel"] is False
        assert mask["backbone"]["layer2_block0"]["conv1"]["kernel"] is True
        assert mask["mask_head"]["conv1"]["kernel"] is True

    def test_resnet50_freeze_set_matches_reference(self):
        """On the real R50 tree, conv1+bn1+layer1 freezes exactly the stem
        and stage-1 params (reference fixed_param_prefix), nothing more."""
        from mx_rcnn_tpu.config import BackboneConfig
        from mx_rcnn_tpu.models.build import build_backbone

        m = build_backbone(BackboneConfig(name="resnet50", dtype="float32"),
                           out_levels=(4,))
        variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
        params = {"backbone": variables["params"]}
        mask = frozen_mask(
            params, ("backbone/conv1", "backbone/bn1", "backbone/layer1")
        )
        flat = jax.tree_util.tree_flatten_with_path(mask)[0]
        for path, trainable in flat:
            stage = path[1].key  # component under "backbone"
            frozen_expected = stage in ("conv1", "bn1") or stage.startswith("layer1_")
            assert trainable is (not frozen_expected), jax.tree_util.keystr(path)

    def test_masked_optimizer_keeps_frozen(self):
        params = {"frozen_w": jnp.ones(4), "free_w": jnp.ones(4)}
        cfg = TrainConfig(schedule=ScheduleConfig(base_lr=0.1, warmup_steps=1))
        tx, _ = make_optimizer(cfg, params, freeze_prefixes=("frozen_",))
        state = tx.init(params)
        grads = {"frozen_w": jnp.ones(4), "free_w": jnp.ones(4)}
        updates, _ = tx.update(grads, state, params)
        new = optax.apply_updates(params, updates)
        np.testing.assert_allclose(new["frozen_w"], params["frozen_w"])
        assert not np.allclose(new["free_w"], params["free_w"])


class TestTrainState:
    def _toy_state(self):
        params = {"w": jnp.asarray([1.0, 2.0])}
        tx = optax.sgd(0.1, momentum=0.9)
        return (
            TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                model_state={},
                opt_state=tx.init(params),
                rng=jax.random.PRNGKey(0),
            ),
            tx,
        )

    def test_apply_gradients_descends(self):
        state, tx = self._toy_state()

        def loss(p):
            return jnp.sum(p["w"] ** 2)

        for _ in range(80):  # momentum SGD oscillates on a quadratic; let it settle
            grads = jax.grad(loss)(state.params)
            state = state.apply_gradients(grads, tx)
        assert float(loss(state.params)) < 0.1
        assert int(state.step) == 80

    def test_checkpoint_roundtrip(self, tmp_path):
        state, tx = self._toy_state()
        grads = jax.grad(lambda p: jnp.sum(p["w"] ** 2))(state.params)
        state = state.apply_gradients(grads, tx)
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(ckpt, state, wait=True)
        assert latest_step(ckpt) == 1
        target, _ = self._toy_state()
        restored = restore_checkpoint(ckpt, target)
        assert int(restored.step) == 1
        np.testing.assert_allclose(restored.params["w"], state.params["w"])
        # Momentum survives resume (the reference loses it, SURVEY.md §6).
        jax.tree_util.tree_map(
            np.testing.assert_allclose, restored.opt_state, state.opt_state
        )


class TestWeightDecayMask:
    def test_bias_and_scale_not_decayed(self):
        params = {"layer": {"kernel": jnp.ones(2), "bias": jnp.ones(2), "scale": jnp.ones(2)}}
        cfg = TrainConfig(
            weight_decay=0.5, momentum=0.0, grad_clip=1e9,
            schedule=ScheduleConfig(base_lr=1.0, warmup_steps=0, warmup_factor=1.0),
        )
        tx, _ = make_optimizer(cfg, params)
        zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        updates, _ = tx.update(zero_grads, tx.init(params), params)
        # Kernel gets a wd pull, bias/scale don't.
        assert np.all(np.asarray(updates["layer"]["kernel"]) != 0)
        np.testing.assert_allclose(updates["layer"]["bias"], 0)
        np.testing.assert_allclose(updates["layer"]["scale"], 0)


@pytest.mark.slow
class TestResumeContinuity:
    def test_resume_matches_uninterrupted(self, tmp_path):
        """train(6) == train(3) + resume(3): same data schedule, same step
        count, same final loss scale (bitwise params equality also holds
        because optimizer state incl. momentum is checkpointed)."""
        import dataclasses

        import jax

        from mx_rcnn_tpu.config import get_config
        from mx_rcnn_tpu.train.loop import train

        def cfg_for(workdir):
            cfg = get_config("tiny_synthetic", workdir=str(workdir))
            sched = dataclasses.replace(
                cfg.train.schedule, total_steps=6, warmup_steps=2,
                decay_steps=(6,),
            )
            return dataclasses.replace(
                cfg,
                train=dataclasses.replace(
                    cfg.train, schedule=sched, checkpoint_every=3, log_every=10
                ),
            )

        cfg_a = cfg_for(tmp_path / "a")
        full = train(cfg_a, mesh=None, total_steps=6, workdir=cfg_a.workdir)

        cfg_b = cfg_for(tmp_path / "b")
        train(cfg_b, mesh=None, total_steps=3, workdir=cfg_b.workdir)
        resumed = train(
            cfg_b, mesh=None, total_steps=6, workdir=cfg_b.workdir, resume=True
        )

        assert int(full.step) == int(resumed.step) == 6
        la = jax.tree_util.tree_leaves(jax.device_get(full.params))
        lb = jax.tree_util.tree_leaves(jax.device_get(resumed.params))
        for a, b in zip(la, lb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.slow
class TestStepsPerCall:
    def test_scan_loop_matches_sequential(self, tmp_path):
        """train.steps_per_call=2 (device-side lax.scan step loop) must
        produce the same params as the per-step host loop on the same data
        schedule."""
        from mx_rcnn_tpu.config import get_config
        from mx_rcnn_tpu.train.loop import train

        def cfg_for(workdir, k):
            cfg = get_config("tiny_synthetic")
            sched = dataclasses.replace(
                cfg.train.schedule, total_steps=4, warmup_steps=1, decay_steps=(3,)
            )
            return dataclasses.replace(
                cfg,
                workdir=str(workdir),
                train=dataclasses.replace(
                    cfg.train, schedule=sched, steps_per_call=k,
                    checkpoint_every=100, log_every=2,
                ),
            )

        cfg1 = cfg_for(tmp_path / "seq", 1)
        seq = train(cfg1, mesh=None, total_steps=4, workdir=cfg1.workdir)
        cfg2 = cfg_for(tmp_path / "scan", 2)
        scanned = train(cfg2, mesh=None, total_steps=4, workdir=cfg2.workdir)

        assert int(seq.step) == int(scanned.step) == 4
        fa = jax.tree_util.tree_flatten_with_path(jax.device_get(seq.params))[0]
        fb = dict(
            jax.tree_util.tree_flatten_with_path(jax.device_get(scanned.params))[0]
        )
        for path, a in fa:
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(fb[path]), atol=1e-5,
                err_msg=jax.tree_util.keystr(path),
            )


class TestConfigDriftWarning:
    def test_warns_on_changed_field(self, tmp_path, caplog):
        import dataclasses as dc
        import json
        import logging

        from mx_rcnn_tpu.config import get_config
        from mx_rcnn_tpu.train.loop import _warn_config_drift

        cfg = get_config("tiny_synthetic")
        path = str(tmp_path / "config.json")
        with open(path, "w") as f:
            json.dump(dc.asdict(cfg), f)

        changed = dc.replace(
            cfg, train=dc.replace(cfg.train, per_device_batch=2)
        )
        with caplog.at_level(logging.WARNING, logger="mx_rcnn_tpu"):
            _warn_config_drift(changed, path)
        assert any("per_device_batch" in r.message for r in caplog.records)

        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="mx_rcnn_tpu"):
            _warn_config_drift(cfg, path)  # unchanged: silent
        assert not caplog.records
