"""Tensor-cache prewarm + LRU eviction for COCO-scale datasets.

A cold :class:`~mx_rcnn_tpu.data.cache.TensorCache` makes the first
training epoch pay full decode+letterbox cost per image; this tool pays
it up front, in parallel, through the SAME path production uses — the
process input service (data/service.py) with the cache directory shared
between workers.  Records whose blobs already exist are skipped (the
assembly path's ``cache.key``/``get`` hit short-circuits the decode), so
re-running after an interrupted warm only fills the holes.

With ``--max-bytes`` the tool then trims the cache directory to a byte
budget by evicting the least-recently-used blobs (mtime order — reads
via the loader touch blobs through the OS, and a warm rewrites them), and
emits one journaled ``cache_evict`` event so the obs plane records what
was dropped and why (tools/obs_report.py lists it in the incident
timeline).  Eviction is safe against concurrent readers: a reader that
loses a blob sees a plain cache miss and rebuilds from source.

Prints diagnostics to stderr and exactly one JSON summary as the LAST
line on stdout:

    {"metric": "cache_warm", "value": {"records": 64, "blobs": 128,
     "already_cached": 0, "warmed_s": 3.2, "evicted": 10,
     "freed_bytes": 81920, "used_bytes": 524288}, ...}

Usage:
    python tools/cache_warm.py --cache-dir /data/cache --images 64 \\
        --workers 4 --epochs 2 --max-bytes 268435456
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

log = logging.getLogger("cache_warm")


def _blobs(cache_dir_root: str) -> list[tuple[str, int, float]]:
    """Every blob under the cache root (all transform fingerprints):
    (path, size, mtime) — eviction order is mtime-LRU across the lot."""
    out = []
    tensors = os.path.join(cache_dir_root, "tensors")
    for dirpath, _dirnames, filenames in os.walk(tensors):
        for fn in filenames:
            if not fn.endswith(".blob"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                st = os.stat(path)
            except OSError:
                continue  # a concurrent evict/replace won the race
            out.append((path, st.st_size, st.st_mtime))
    return out


def warm(args) -> dict:
    """Drive --epochs of the train stream through the input service with
    the cache attached; every assembled batch populates the shared disk
    cache as a side effect.  Returns warm-phase stats."""
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.data import DetectionLoader
    from mx_rcnn_tpu.data.cache import TensorCache
    from train_soak import make_roidb

    cfg = get_config(args.config)
    data_cfg = dataclasses.replace(
        cfg.data, dataset="synthetic", cache_dir=args.cache_dir
    )
    roidb = make_roidb(cfg, args.images, seed=args.seed)
    cache = TensorCache(args.cache_dir, data_cfg)
    already = sum(
        1
        for rec in roidb
        for flip in (False, True)
        if os.path.exists(cache._path(cache.key(rec, flip)))
    )
    loader = DetectionLoader(
        roidb, data_cfg, batch_size=args.batch_size, train=True,
        seed=args.seed, prefetch=False, num_workers=0,
        service_workers=args.workers,
    )
    t0 = time.monotonic()
    batches = 0
    for _ in loader._raw_train_batches(0, epochs=args.epochs):
        batches += 1  # batches populate the cache; content is discarded
    warmed_s = time.monotonic() - t0
    blobs = _blobs(args.cache_dir)
    return {
        "records": len(roidb),
        "epochs": args.epochs,
        "batches": batches,
        "already_cached": already,
        "blobs": len(blobs),
        "used_bytes": sum(s for _, s, _ in blobs),
        "warmed_s": round(warmed_s, 3),
    }


def evict(cache_dir_root: str, max_bytes: int) -> dict:
    """Trim the cache to ``max_bytes`` by deleting blobs oldest-mtime
    first; one journaled ``cache_evict`` event summarizes the sweep."""
    from mx_rcnn_tpu import obs

    blobs = sorted(_blobs(cache_dir_root), key=lambda b: b[2])  # LRU first
    used = sum(s for _, s, _ in blobs)
    evicted = 0
    freed = 0
    for path, size, _mtime in blobs:
        if used - freed <= max_bytes:
            break
        try:
            os.unlink(path)
        except OSError:
            continue  # reader/rewarm race: it no longer counts anyway
        evicted += 1
        freed += size
    if evicted:
        obs.emit("data", "cache_evict", {
            "evicted": evicted,
            "freed_bytes": freed,
            "used_bytes": used - freed,
            "max_bytes": max_bytes,
        }, logger=log)
    return {
        "evicted": evicted,
        "freed_bytes": freed,
        "used_bytes": used - freed,
        "max_bytes": max_bytes,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="tiny_synthetic")
    p.add_argument("--cache-dir", required=True,
                   help="TensorCache root (data.cache_dir)")
    p.add_argument("--images", type=int, default=64,
                   help="synthetic dataset size to warm")
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--epochs", type=int, default=2,
                   help="train-stream epochs to run (flip augmentation "
                        "means later epochs fill the other flip variants)")
    p.add_argument("--workers", type=int, default=2,
                   help="input-service decode workers (0 = in-process)")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--max-bytes", type=int, default=0,
                   help="evict LRU blobs until the cache fits this "
                        "budget (0 = no eviction)")
    p.add_argument("--obs-dir", default=None,
                   help="journal cache_evict events here")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from mx_rcnn_tpu import obs

    obs_on = bool(args.obs_dir)
    if obs_on:
        obs.configure(args.obs_dir, flush_s=5.0)

    os.makedirs(args.cache_dir, exist_ok=True)
    stats = warm(args)
    log.info(
        "warmed %d record(s) x %d epoch(s) in %.2fs: %d blob(s), %dB "
        "(%d already cached)",
        stats["records"], stats["epochs"], stats["warmed_s"],
        stats["blobs"], stats["used_bytes"], stats["already_cached"],
    )
    if args.max_bytes > 0:
        ev = evict(args.cache_dir, args.max_bytes)
        log.info(
            "evicted %d blob(s), freed %dB -> %dB used (budget %dB)",
            ev["evicted"], ev["freed_bytes"], ev["used_bytes"],
            ev["max_bytes"],
        )
        stats.update(ev)
    if obs_on:
        obs.close()
    print(json.dumps({
        "metric": "cache_warm",
        "value": stats,
        "cache_dir": os.path.abspath(args.cache_dir),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
