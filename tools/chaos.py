"""Chaos harness: fault-inject a real training subprocess, prove recovery.

The robustness claims in docs/robustness.md are cheap to assert and easy
to regress silently — so this harness drives the REAL CLI (`train_cli`)
as a subprocess on hermetic CPU (tiny_synthetic preset) and injects the
faults the runtime is supposed to survive:

  baseline  uninterrupted run; its final checkpoint is the bitwise oracle
            for every recovery scenario below.
  sigkill   SIGKILL (no grace, mid-flight) once a mid-run checkpoint
            lands; resume with --resume; final params must be
            BIT-IDENTICAL to baseline's.
  sigterm   SIGTERM mid-run; the child must drain the in-flight step,
            write the emergency checkpoint and exit RESUMABLE_EXIT_CODE;
            resume; bit-identical final params.
  nan       arm the loader's NaN hook (MX_RCNN_CHAOS_NAN_STEPS) for one
            batch; the guardian must roll back, skip the window and
            finish with every logged metric finite.
  truncate  SIGKILL mid-run, then truncate the newest checkpoint's files
            (simulating a kill inside the write); the resumed child must
            fall back to the previous step and STILL converge to
            baseline's exact params.

Data-path scenarios (data/service.py, data/cache.py) — every train child
above already runs the production input path (process decode workers +
checksummed tensor cache via ``--set data.num_workers/cache_dir``), so
baseline vs sigkill/sigterm doubles as the cache-hit-vs-miss bitwise
proof; these four inject data-specific faults on top:

  data_worker_kill   a decode worker SIGKILLs itself mid-epoch (armed via
                     MX_RCNN_CHAOS_DATA_SUICIDE); its in-flight batches
                     are reassigned deterministically and the final
                     params are BIT-IDENTICAL to baseline's.
  data_worker_wedge  a worker wedges (no heartbeat); the watchdog reaps
                     + respawns it, the run completes bit-identical, and
                     the per-interval data_stall_ms stays bounded (the
                     wedge never leaks into the wait).
  cache_corrupt      flip bytes inside a cached tensor blob; the next run
                     detects the bad checksum, quarantines + rebuilds the
                     blob, completes, and stays bit-identical — corrupt
                     bytes are never served.
  data_service_dead  every worker dies until the respawn budget is
                     exhausted (suicide "always"); the service degrades
                     to in-process synchronous assembly and the run
                     STILL completes bit-identical.

Inference scenarios (docs/serving.md) — same real-subprocess discipline:

  eval_sigkill  SIGKILL a --resumable eval once shard checkpoints are on
                disk; re-run with --resume; the final detections JSON
                must be BYTE-IDENTICAL to an uninterrupted eval's.
  eval_corrupt  poison images via MX_RCNN_CHAOS_BAD_IMAGES; eval must
                finish cleanly, quarantine the ids, and still dump every
                scheduled image.
  overload      flood a real engine past its bounded queue; at least one
                request must be shed (typed Overloaded) and every
                admitted request must complete — no deadlock.
  hang          serve through a runner whose device call never returns;
                the watchdog must declare the engine dead and fail the
                waiter with a typed error instead of hanging the client.

Fleet scenarios (serve/fleet.py) — real fleets on 4 fake CPU devices
(``--xla_force_host_platform_device_count``, one replica per device):

  replica_kill    kill 1 of 4 replicas mid-load: every ACCEPTED request
                  still completes (failover retry), the replica is
                  quarantined, rebuilt and reinstated.
  replica_wedge   one replica's device calls hang: hedged retries keep
                  latency bounded, the watchdog + supervisor quarantine
                  the wedge, the rebuild reinstates it.
  swap_under_load zero-downtime weight swap mid-traffic: every response
                  bitwise-matches the old-weights or new-weights oracle
                  for its generation — no request ever sees a
                  half-swapped tree.
  fleet_drain     SIGTERM during load: the fleet stops admitting,
                  every accepted request completes, and the process
                  exits RESUMABLE_EXIT_CODE (75) — the trainer's
                  preemption contract, applied to serving.
  fleet_scale     autoscaler closed loop (ctrl/autoscale.py): a queue
                  spike forces a scale-up onto a spare device, idleness
                  dwells into a scale-down drain, zero accepted
                  requests lost — and the full resize story replays
                  from the obs journal.

Cross-host fabric scenarios (serve/rpc.py, serve/gossip.py,
serve/gateway.py) — REAL multi-process fleets: each host is a
tools/serve_host.py subprocess (its own interpreter, devices and RPC
port); the chaos child drives them through a real GatewayRouter:

  host_kill        SIGKILL one of two host processes mid-load through
                   the gateway: zero accepted-request loss (cross-host
                   retry), gossip flags the host dead, the gateway
                   quarantines it and rebalances onto the survivor —
                   which then drains on SIGTERM and exits 75.
  host_partition   SIGSTOP a host (alive but silent — a network
                   partition, not a crash): gossip walks it through
                   suspect -> dead, the gateway fences it, traffic
                   keeps completing on the peer; SIGCONT heals the
                   partition and the probe loop reinstates the host.
  cross_host_swap  pod-wide generation-tagged weight roll under load:
                   every response from EITHER host bitwise-matches the
                   oracle for the generation it reports — proving hosts
                   serve identical weights per generation and no
                   response ever mixes generations.

Bit-identity holds because recovery re-runs the same compiled program
over the same data schedule from the same restored state — it is the
strongest possible "nothing was lost, nothing was double-applied" check
and it needs no tolerance tuning.

Usage:
  python tools/chaos.py [--scenario all|baseline|sigkill|sigterm|nan|truncate
                                    |data_worker_kill|data_worker_wedge
                                    |cache_corrupt|data_service_dead
                                    |eval_sigkill|eval_corrupt|overload|hang
                                    |replica_kill|replica_wedge
                                    |swap_under_load|fleet_drain|fleet_scale
                                    |host_kill|host_partition
                                    |cross_host_swap]
                        [--steps 12] [--workdir DIR] [--keep] [--timeout 900]
                        [--scenario-timeout SECONDS] [--lockcheck auto|on|off]

``--scenario`` also takes a comma-separated list (e.g.
``--scenario data_worker_kill,cache_corrupt``) — scenarios share the
workdir, so baseline runs once and is reused.

Every scenario runs under a per-scenario wall-clock budget
(``--scenario-timeout``, default 1.5x ``--timeout``); on expiry the
orphan reaper SIGKILLs every live child so one wedged scenario cannot
hang the harness past its budget.

The fleet/fabric scenarios additionally run their children under the
runtime lock-order sanitizer (``--lockcheck auto``, the default, sets
``MX_RCNN_LOCKCHECK=1`` — see mx_rcnn_tpu/analysis/lockcheck.py): a
lock-order inversion or a blocking call under a held lock raises in the
child AND lands in the obs journal, and either fails the scenario.

Prints one JSON summary line on stdout; exits non-zero if any scenario
fails.  (`--child*` / `--compare` are internal subprocess entry modes.)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = "tiny_synthetic"
CKPT_EVERY = 3
LOG_EVERY = 2
EVAL_LIMIT = 16  # images per chaos eval (shard_size=1 -> one shard each)


def _hermetic_cpu() -> None:
    """CPU-only jax in THIS interpreter (same guards as tests/conftest.py:
    the image's sitecustomize registers a TPU-tunnel PJRT plugin whose
    retries can block even cpu backend init)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, REPO_ROOT)
    import jax
    from jax._src import xla_bridge as _xb

    assert isinstance(_xb._backend_factories, dict)
    for name in list(_xb._backend_factories):
        if name not in ("cpu", "tpu"):
            _xb._backend_factories.pop(name, None)
    jax.config.update("jax_platforms", "cpu")
    from mx_rcnn_tpu.utils.compile_cache import configure_cpu_cache

    configure_cpu_cache(REPO_ROOT)


def _fleet_cpu(n_devices: int = 4) -> None:
    """Hermetic CPU with ``n_devices`` fake devices (one per replica).
    Must run before the first jax import — the XLA flag is read at
    backend init."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    _hermetic_cpu()


def _init_variables(cfg, seed: int):
    import jax
    from mx_rcnn_tpu.detection import TwoStageDetector, init_detector

    return init_detector(
        TwoStageDetector(cfg=cfg.model), jax.random.PRNGKey(seed),
        cfg.data.image_size,
    )


# -- internal subprocess modes ------------------------------------------------


def child_main(argv: list[str]) -> int:
    """Run the real train CLI hermetically (the orchestrator's workload)."""
    _hermetic_cpu()
    from mx_rcnn_tpu.cli import train_cli

    return train_cli.cli(argv)


def child_eval_main(argv: list[str]) -> int:
    """Run the real eval CLI hermetically (resumable-eval scenarios)."""
    _hermetic_cpu()
    from mx_rcnn_tpu.cli import eval_cli

    return eval_cli.cli(argv)


def child_overload_main() -> int:
    """Flood a REAL engine (tiny model, random params) past its queue.

    Prints one JSON line: submitted/shed/served counts and engine stats.
    Exits 0 only if >=1 request was shed AND every admitted request
    completed — returning at all is the no-deadlock proof."""
    _hermetic_cpu()
    import numpy as np

    import jax
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.detection import TwoStageDetector, init_detector
    from mx_rcnn_tpu.serve import Overloaded, build_engine

    cfg = get_config(CONFIG)
    variables = init_detector(
        TwoStageDetector(cfg=cfg.model), jax.random.PRNGKey(0),
        cfg.data.image_size,
    )
    img = np.random.default_rng(0).uniform(
        0, 255, (100, 100, 3)
    ).astype(np.float32)
    submitted = 12
    shed = 0
    reqs = []
    with build_engine(cfg, variables, max_queue=2) as engine:
        # The burst is orders of magnitude faster than one device call, so
        # the 2-deep queue must overflow deterministically.
        for _ in range(submitted):
            try:
                reqs.append(engine.submit(img))
            except Overloaded:
                shed += 1
        served = sum(1 for r in reqs if r.result(timeout=300))
        stats = engine.stats()
    print(json.dumps({
        "submitted": submitted, "shed": shed, "served": served,
        "stats_shed": stats["shed"], "state": stats["state"],
    }))
    assert shed >= 1, "queue never overflowed — admission control untested"
    assert served == submitted - shed, "admitted request lost (deadlock?)"
    assert stats["shed"] == shed
    return 0


def child_hang_main() -> int:
    """Serve through a runner whose device call never returns; the
    watchdog must fail the waiter and declare the engine dead."""
    _hermetic_cpu()
    import threading

    import numpy as np
    from mx_rcnn_tpu.serve import EngineUnavailable, InferenceEngine

    class HangingRunner:
        """Runner-protocol stub wedged like a hung device stream."""

        buckets = [(64, 64)]
        batch_size = 1

        def levels(self):
            return ("full", "reduced")

        def pick_bucket(self, h, w):
            return (64, 64)

        def smaller_bucket(self, bucket):
            return None

        def warmup(self):
            return 1

        def run(self, mode, bucket, images):
            threading.Event().wait()  # never returns

    engine = InferenceEngine(
        HangingRunner(), hang_timeout=1.0, watchdog_poll=0.1
    ).start()
    req = engine.submit(np.zeros((32, 32, 3), np.float32))
    try:
        req.result(timeout=60)
        print(json.dumps({"ok": False, "why": "hung request returned"}))
        return 1
    except EngineUnavailable:
        pass
    stats = engine.stats()
    print(json.dumps({"hung": stats["hung"], "state": stats["state"]}))
    assert stats["hung"] == 1, stats
    assert stats["state"] == "dead", stats
    # No engine.stop(): the worker daemon thread is wedged by design and
    # must not block process exit.
    return 0


def child_replica_kill_main() -> int:
    """Kill 1 of 4 replicas mid-load: zero failed ACCEPTED requests.

    The killed replica's queued/in-flight work fails over via the fleet's
    retry; the supervisor quarantines, rebuilds and reinstates it."""
    _fleet_cpu(4)
    import numpy as np
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.serve import build_fleet

    obs_dir = os.environ.get("MX_RCNN_OBS_DIR")
    if obs_dir:
        # Durable observability plane: the parent scenario asserts the
        # journal + flight-recorder artifacts reconstruct the incident.
        obs.configure(obs_dir)
        obs.install_crash_handler()

    cfg = get_config(CONFIG)
    variables = _init_variables(cfg, seed=0)
    img = np.random.default_rng(0).uniform(
        0, 255, (100, 100, 3)
    ).astype(np.float32)
    fleet = build_fleet(
        cfg, variables, n_replicas=4,
        engine_kwargs={"hang_timeout": 300.0},
        supervisor_poll=0.1,
    )
    with fleet:
        accepted = [fleet.submit(img, timeout=300) for _ in range(6)]
        wait_for(lambda: any(r.done() for r in accepted), 300)
        fleet.kill_replica(2, "chaos: replica kill mid-load")
        accepted += [fleet.submit(img, timeout=300) for _ in range(8)]
        results = [r.result(timeout=300) for r in accepted]
        reinstated = wait_for(
            lambda: fleet.stats()["reinstatements"] >= 1, 300
        )
        s = fleet.stats()
    print(json.dumps({
        "accepted": len(accepted), "completed": len(results),
        "failed": s["failed"], "retries": s["retries"],
        "quarantines": s["quarantines"],
        "reinstatements": s["reinstatements"],
        "replicas_used": sorted({r["replica_id"] for r in results}),
    }))
    assert len(results) == len(accepted), "an accepted request was lost"
    assert s["failed"] == 0, f"accepted requests failed: {s}"
    assert s["quarantines"] >= 1, s
    assert reinstated, "killed replica was never reinstated"
    if obs_dir:
        obs.close()
    return 0


def child_replica_wedge_main() -> int:
    """One replica's device calls hang forever: hedging keeps latency
    bounded, the watchdog + supervisor quarantine the wedge, and the
    background rebuild reinstates the replica."""
    _fleet_cpu(4)
    import numpy as np

    import jax
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.serve import FleetRouter, InferenceEngine
    from mx_rcnn_tpu.serve.engine import DetectorRunner

    cfg = get_config(CONFIG)
    variables = _init_variables(cfg, seed=0)
    release = threading.Event()
    builds = {"n": 0}

    class WedgedRunner:
        """Delegates to a real runner, but every device call hangs until
        released — a wedged device stream."""

        def __init__(self, inner) -> None:
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def run(self, mode, bucket, images):
            release.wait()
            return self._inner.run(mode, bucket, images)

    devices = jax.devices()

    def factory(rid: int) -> InferenceEngine:
        runner = DetectorRunner(
            cfg, variables, device=devices[rid % len(devices)]
        )
        builds["n"] += 1
        if rid == 0 and builds["n"] == 1:
            runner = WedgedRunner(runner)  # only the FIRST build wedges
        return InferenceEngine(
            runner, replica_id=rid, hang_timeout=3.0, watchdog_poll=0.1
        )

    fleet = FleetRouter(
        factory, 2, hedge_after=1.0, supervisor_poll=0.1
    )
    lat = []
    with fleet:
        t0 = time.monotonic()
        reqs = [fleet.submit(img, timeout=120) for img in [
            np.random.default_rng(i).uniform(
                0, 255, (100, 100, 3)
            ).astype(np.float32) for i in range(8)
        ]]
        for r in reqs:
            r.result(timeout=240)
            lat.append(time.monotonic() - t0)
        quarantined = wait_for(
            lambda: fleet.stats()["quarantines"] >= 1, 120
        )
        release.set()  # un-wedge so the stuck worker thread can exit
        reinstated = wait_for(
            lambda: fleet.stats()["reinstatements"] >= 1, 300
        )
        s = fleet.stats()
    lat.sort()
    p99 = lat[min(len(lat) - 1, int(round(0.99 * (len(lat) - 1))))]
    print(json.dumps({
        "completed": len(lat), "failed": s["failed"],
        "hedges": s["hedges"], "hedge_wins": s["hedge_wins"],
        "quarantines": s["quarantines"],
        "reinstatements": s["reinstatements"],
        "p99_s": round(p99, 3),
    }))
    assert s["failed"] == 0, s
    assert s["hedges"] >= 1, f"wedge never triggered a hedge: {s}"
    assert quarantined, "wedged replica was never quarantined"
    assert reinstated, "wedged replica was never reinstated"
    assert p99 < 60.0, (
        f"p99 {p99:.1f}s unbounded — hedging failed to contain the wedge"
    )
    return 0


def child_tenant_starvation_main() -> int:
    """Noisy-neighbor isolation on a real fleet: a flooder saturating its
    quota must not move the victims' completion rate or latency.

    Phase A runs the victims alone (flooder-free baseline p99); Phase B
    replays the identical victim load while the flooder fires point-blank
    bursts between every victim submit.  The flooder's excess bounces off
    its token bucket as QuotaExceeded at the fleet front door; the
    victims complete 100% with zero shed/quota and a p99 within a gated
    factor of the baseline."""
    _fleet_cpu(2)
    import numpy as np
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.serve import (
        Overloaded, QuotaExceeded, TenancyPolicy, build_fleet,
    )
    from mx_rcnn_tpu.serve.tenancy import parse_table

    obs_dir = os.environ.get("MX_RCNN_OBS_DIR")
    if obs_dir:
        # Journaled: the parent scenario re-derives the per-tenant story
        # (quota rejections, outcome counts) from the obs artifacts.
        obs.configure(obs_dir)

    cfg = get_config(CONFIG)
    variables = _init_variables(cfg, seed=0)
    rng = np.random.default_rng(0)

    def fresh_img():
        # Distinct per request so the result cache can't serve hits and
        # flatten the latency comparison between phases.
        return rng.uniform(0, 255, (100, 100, 3)).astype(np.float32)

    # Victims are unlimited (rate<=0); the flooder is rate-capped so its
    # bursts die at the quota gate instead of filling the queues.
    policy = TenancyPolicy(parse_table(
        "victim:weight=4;bursty:weight=2;flood:rate=2,burst=2,priority=2"
    ))
    N_VICTIM, N_BURSTY, FLOOD_BURST = 10, 5, 8
    VICTIMS = ("victim", "bursty")

    fleet = build_fleet(cfg, variables, n_replicas=2, tenancy=policy,
                        engine_kwargs={"hang_timeout": 300.0})

    def run_mix(flood: bool) -> dict:
        per = {t: {"submitted": 0, "completed": 0, "shed": 0, "quota": 0,
                   "lat": []} for t in ("victim", "bursty", "flood")}
        pending = []

        def sub(tenant):
            per[tenant]["submitted"] += 1
            try:
                req = fleet.submit(fresh_img(), timeout=300, tenant=tenant)
            except QuotaExceeded:
                per[tenant]["quota"] += 1
                return
            except Overloaded:
                per[tenant]["shed"] += 1
                return
            pending.append((tenant, time.monotonic(), req))

        for i in range(N_VICTIM):
            sub("victim")
            if i % 2 == 0 and per["bursty"]["submitted"] < N_BURSTY:
                sub("bursty")
            if flood:
                for _ in range(FLOOD_BURST):
                    sub("flood")
            time.sleep(0.05)
        for tenant, t0, req in pending:
            req.result(timeout=300)
            per[tenant]["completed"] += 1
            per[tenant]["lat"].append(time.monotonic() - t0)
        for t, d in per.items():
            lat = sorted(d.pop("lat"))
            d["p99_s"] = round(
                lat[min(len(lat) - 1, int(round(0.99 * (len(lat) - 1))))], 4
            ) if lat else None
        return per

    with fleet:
        base = run_mix(flood=False)
        mix = run_mix(flood=True)
        s = fleet.stats()

    baseline_p99 = max(b["p99_s"] for t, b in base.items() if t in VICTIMS)
    mix_p99 = max(m["p99_s"] for t, m in mix.items() if t in VICTIMS)
    print(json.dumps({
        "baseline_p99_s": baseline_p99, "mix_p99_s": mix_p99,
        "victims": {t: mix[t] for t in VICTIMS},
        "flooder": mix["flood"],
        "fleet": {"shed": s["shed"], "quota": s["quota"],
                  "failed": s["failed"]},
    }))
    for t in VICTIMS:
        for phase in (base, mix):
            v = phase[t]
            assert v["completed"] == v["submitted"], (t, phase)
            assert v["quota"] == 0 and v["shed"] == 0, (t, phase)
    assert mix["flood"]["quota"] >= FLOOD_BURST, (
        f"flooder was never quota-capped: {mix['flood']}"
    )
    assert s["shed"] == 0 and s["failed"] == 0, s
    # 0.25s floor: at CPU-scale latencies, scheduler noise would flap a
    # pure ratio gate long before real starvation shows.
    assert mix_p99 <= 3.0 * max(baseline_p99, 0.25), (
        f"victims starved: mix p99 {mix_p99}s vs baseline {baseline_p99}s"
    )
    if obs_dir:
        obs.close()
    return 0


def child_swap_main() -> int:
    """Zero-downtime weight swap under load: every response must
    bitwise-match the old-weights or new-weights oracle for the
    generation it reports — a half-swapped tree would match neither."""
    _fleet_cpu(4)
    import numpy as np
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.serve import build_fleet

    obs_dir = os.environ.get("MX_RCNN_OBS_DIR")
    if obs_dir:
        # Journaled so the parent's lock-sanitizer sweep sees swap-path
        # violations even from threads that swallow exceptions.
        obs.configure(obs_dir)

    cfg = get_config(CONFIG)
    v0 = _init_variables(cfg, seed=0)
    v1 = _init_variables(cfg, seed=1)
    probe = np.random.default_rng(7).uniform(
        0, 255, (96, 128, 3)
    ).astype(np.float32)
    KEYS = ("boxes", "scores", "classes")

    def sig(res):
        return {k: np.asarray(res[k]) for k in KEYS}

    def matches(res, oracle) -> bool:
        return all(
            np.array_equal(np.asarray(res[k]), oracle[k]) for k in KEYS
        )

    fleet = build_fleet(
        cfg, v0, n_replicas=2,
        engine_kwargs={"hang_timeout": 300.0},
        supervisor_poll=0.1,
    )
    results: list[dict] = []
    errors: list[str] = []
    stop = threading.Event()

    def pump() -> None:
        while not stop.is_set():
            try:
                results.append(fleet.infer(probe, timeout=300))
            except Exception as e:  # noqa: BLE001 - report, don't die
                errors.append(f"{type(e).__name__}: {e}")
                return

    with fleet:
        oracle = {0: sig(fleet.infer(probe, timeout=300))}
        pumps = [
            threading.Thread(target=pump, daemon=True) for _ in range(2)
        ]
        for t in pumps:
            t.start()
        wait_for(lambda: len(results) >= 2, 300)
        gen = fleet.swap_weights(v1)  # mid-load, rolled replica by replica
        wait_for(
            lambda: any(
                r.get("generation") == gen for r in list(results)
            ),
            300,
        )
        stop.set()
        for t in pumps:
            t.join(300)
        oracle[gen] = sig(fleet.infer(probe, timeout=300))
    gens = sorted({r["generation"] for r in results})
    mismatched = [
        i for i, r in enumerate(results)
        if r["generation"] not in oracle
        or not matches(r, oracle[r["generation"]])
    ]
    print(json.dumps({
        "responses": len(results), "generations_seen": gens,
        "mismatched": mismatched, "errors": errors,
        "swap_generation": gen,
    }))
    assert not errors, f"requests failed during the swap: {errors}"
    assert gens == [0, gen], (
        f"expected traffic on both sides of the swap, saw {gens}"
    )
    assert not mismatched, (
        f"{len(mismatched)} responses matched NEITHER weight version — "
        "a request saw a half-swapped tree"
    )
    return 0


def child_fleet_drain_main() -> int:
    """SIGTERM during load: stop admitting, complete every accepted
    request, exit RESUMABLE_EXIT_CODE — the trainer's preemption
    contract (train/preemption.py), applied to the serving fleet."""
    _fleet_cpu(4)
    import numpy as np
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.serve import Overloaded, build_fleet
    from mx_rcnn_tpu.train.preemption import (
        RESUMABLE_EXIT_CODE,
        PreemptionGuard,
    )

    cfg = get_config(CONFIG)
    variables = _init_variables(cfg, seed=0)
    img = np.random.default_rng(0).uniform(
        0, 255, (100, 100, 3)
    ).astype(np.float32)
    fleet = build_fleet(
        cfg, variables, n_replicas=2,
        engine_kwargs={"hang_timeout": 300.0},
        supervisor_poll=0.1,
    )
    accepted = []
    with PreemptionGuard() as guard:
        fleet.start()
        print("FLEET_READY", flush=True)
        while not guard.triggered and len(accepted) < 500:
            try:
                accepted.append(fleet.submit(img, timeout=300))
            except Overloaded:
                time.sleep(0.2)
                continue
            time.sleep(0.05)
        clean = fleet.drain(timeout=240)
    failed = 0
    for r in accepted:
        try:
            r.result(timeout=1)
        except Exception:  # noqa: BLE001 - counted, asserted below
            failed += 1
    print(json.dumps({
        "accepted": len(accepted), "failed": failed,
        "drained_clean": bool(clean),
        "signal": guard.signum,
    }), flush=True)
    assert guard.triggered, "drain ran without a signal — test is vacuous"
    assert clean, "drain left pending requests behind"
    assert failed == 0, f"{failed} accepted requests failed during drain"
    return RESUMABLE_EXIT_CODE


def child_fleet_scale_main() -> int:
    """Autoscaler closed loop on a real fleet: a queue spike forces a
    scale-up (background build joins the rotation), idleness then walks
    the dwell counter to a scale-down (drain + slot release) — with
    zero accepted requests lost across both resizes."""
    _fleet_cpu(4)
    import numpy as np
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.ctrl import Autoscaler, ScalePolicy
    from mx_rcnn_tpu.serve import build_fleet

    obs_dir = os.environ.get("MX_RCNN_OBS_DIR")
    if obs_dir:
        obs.configure(obs_dir)

    cfg = get_config(CONFIG)
    variables = _init_variables(cfg, seed=0)
    img = np.random.default_rng(0).uniform(
        0, 255, (100, 100, 3)
    ).astype(np.float32)
    fleet = build_fleet(
        cfg, variables, n_replicas=2,
        engine_kwargs={"hang_timeout": 300.0, "max_queue": 64},
        supervisor_poll=0.1,
    )
    # Tight thresholds so a 12-request burst is unambiguous pressure
    # and an idle fleet is unambiguous comfort; no cooldowns, so the
    # test drives the dwell logic alone.
    scaler = Autoscaler(fleet, ScalePolicy(
        min_replicas=2, max_replicas=3,
        load_high=1.0, load_low=0.5,
        down_dwell=2, up_cooldown_s=0.0, down_cooldown_s=0.0,
    ))
    with fleet:
        accepted = [fleet.submit(img, timeout=300) for _ in range(12)]
        rec_up = scaler.step()
        assert rec_up["action"] == "up", rec_up
        new_rid = rec_up["replica"]
        # The new replica builds in the background (warmup compiles)
        # while the burst keeps serving; wait until it joins rotation.
        wait_for(
            lambda: any(
                rep["rid"] == new_rid
                and rep["state"] in ("ready", "degraded")
                for rep in fleet.stats()["replica"]
            ),
            300,
        )
        # Traffic lands on the grown fleet too.
        accepted += [fleet.submit(img, timeout=300) for _ in range(4)]
        results = [r.result(timeout=300) for r in accepted]
        # Idle now: the dwell counter must walk to a scale-down.
        rec_down = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            rec = scaler.step()
            if rec["action"] == "down":
                rec_down = rec
                break
            time.sleep(0.2)
        s = fleet.stats()
    assert rec_down is not None, "autoscaler never scaled down"
    assert rec_down["replica"] == new_rid, rec_down
    assert rec_down.get("clean", False), f"retire drain unclean: {rec_down}"
    print(json.dumps({
        "accepted": len(accepted), "completed": len(results),
        "failed": s["failed"], "added": s["added"],
        "retired": s["retired"], "replicas_final": s["replicas"],
        "scaled_up_rid": new_rid,
        "up_reason": rec_up["reason"], "down_reason": rec_down["reason"],
        "decisions": len(scaler.resize_timeline()),
    }))
    assert len(results) == len(accepted), "an accepted request was lost"
    assert s["failed"] == 0, f"accepted requests failed: {s}"
    assert s["added"] >= 1 and s["retired"] >= 1, s
    assert s["replicas"] == 2, s
    if obs_dir:
        obs.close()
    return 0


# -- cross-host fabric children ----------------------------------------------


SERVE_HOST = os.path.join(REPO_ROOT, "tools", "serve_host.py")


class _FabricHost:
    """One tools/serve_host.py subprocess — a REAL host: its own
    interpreter, fake devices, fleet, RPC port and gossip node.
    Readiness (and the ephemeral port) is parsed from its log."""

    def __init__(self, workdir: str, host_id: str, *, replicas: int = 2,
                 seed: int = 0, peers: str = "") -> None:
        os.makedirs(workdir, exist_ok=True)
        self.host_id = host_id
        self.log_path = os.path.join(workdir, f"{host_id}.log")
        self._log = open(self.log_path, "a")
        argv = [
            sys.executable, SERVE_HOST, "--host-id", host_id,
            "--config", CONFIG, "--replicas", str(replicas),
            "--seed", str(seed), "--port", "0",
        ]
        if peers:
            argv += ["--peers", peers]
        self.proc = subprocess.Popen(
            argv, stdout=self._log, stderr=subprocess.STDOUT, cwd=REPO_ROOT,
        )
        self.port: Optional[int] = None
        self.addr: Optional[str] = None

    def wait_ready(self, timeout: float) -> str:
        def ready_line():
            if self.proc.poll() is not None:
                raise AssertionError(
                    f"{self.host_id} died (rc={self.proc.returncode}) "
                    f"before HOST_READY (log: {self.log_path})\n"
                    f"{self.log_tail()}"
                )
            try:
                with open(self.log_path) as f:
                    for ln in f:
                        if ln.startswith("HOST_READY"):
                            return ln.strip()
            except OSError:
                pass
            return None

        line = wait_for(ready_line, timeout, poll=0.5)
        assert line, (
            f"{self.host_id} not ready within {timeout}s "
            f"(log: {self.log_path})\n{self.log_tail()}"
        )
        for tok in line.split():
            if tok.startswith("port="):
                self.port = int(tok.partition("=")[2])
        assert self.port, f"no port on READY line: {line!r}"
        self.addr = f"127.0.0.1:{self.port}"
        return self.addr

    def log_tail(self, n: int = 30) -> str:
        try:
            with open(self.log_path) as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return ""

    def kill(self) -> None:
        try:
            if self.proc.poll() is None:
                self.proc.kill()
                self.proc.wait(10)
        except Exception:  # noqa: BLE001 - teardown best effort
            pass
        self._log.close()


def _fabric_workdir() -> str:
    return os.environ.get("MX_RCNN_FABRIC_WD") or tempfile.mkdtemp(
        prefix="mx_rcnn_fabric_"
    )


def _collect_results(accepted: list) -> tuple[list, list]:
    results, errors = [], []
    for r in accepted:
        try:
            results.append(r.result(timeout=300))
        except Exception as e:  # noqa: BLE001 - counted, asserted by caller
            errors.append(f"{type(e).__name__}: {e}")
    return results, errors


def child_host_kill_main() -> int:
    """SIGKILL one of two REAL host processes mid-load through the
    gateway: zero accepted-request loss, gossip flags the host dead,
    the gateway quarantines it and rebalances onto the survivor — and
    the survivor then honors the preemption contract (SIGTERM -> drain
    -> exit 75)."""
    _fleet_cpu(2)
    import numpy as np
    from mx_rcnn_tpu.serve import GatewayRouter, GossipNode
    from mx_rcnn_tpu.serve.gossip import DEAD as GOSSIP_DEAD

    wd = _fabric_workdir()
    RESUMABLE_EXIT_CODE = 75  # pinned, mirrors train/preemption.py
    hosts: list[_FabricHost] = []
    try:
        a = _FabricHost(wd, "hostA", replicas=2, seed=0)
        hosts.append(a)
        a.wait_ready(600)
        b = _FabricHost(wd, "hostB", replicas=2, seed=0,
                        peers=f"hostA={a.addr}")
        hosts.append(b)
        b.wait_ready(600)

        # Observer gossip node: proves the mesh (not just the gateway's
        # own request failures) detects the death.
        observer = GossipNode(
            "chaos-observer", "", lambda: {"draining": True},
            peers={"hostA": a.addr, "hostB": b.addr},
            period_s=0.25, suspect_after_s=1.0, dead_after_s=3.0,
        ).start()
        gw = GatewayRouter(
            [a.addr, b.addr], probe_interval_s=0.25, gossip=observer,
        ).start()
        assert wait_for(lambda: gw.stats()["replicas"] == 2, 120), (
            f"gateway never saw both hosts routable: {gw.stats()}"
        )
        img = np.random.default_rng(0).uniform(
            0, 255, (100, 100, 3)
        ).astype(np.float32)
        accepted = [gw.submit(img, timeout=120) for _ in range(6)]
        wait_for(lambda: any(r.done() for r in accepted), 300)
        a.proc.kill()  # a whole failure domain vanishes mid-load
        accepted += [gw.submit(img, timeout=120) for _ in range(8)]
        results, errors = _collect_results(accepted)
        gossip_dead = wait_for(
            lambda: (
                observer.peers().get("hostA") is not None
                and observer.peers()["hostA"].status == GOSSIP_DEAD
            ),
            60,
        )
        quarantined = wait_for(lambda: gw.stats()["quarantines"] >= 1, 60)
        post_kill_hosts = sorted(
            {r["host_id"] for r in results[-8:]}
        ) if len(results) >= 8 else []
        s = gw.stats()
        # Gateway metrics must scrape clean after the failover: the
        # request counter and the gossip peer gauge both rendered, with
        # traffic actually recorded (the CI fabric_smoke gate).
        from mx_rcnn_tpu import obs
        metrics_text = obs.render_metrics()
        metrics_clean = (
            "gateway_requests_total" in metrics_text
            and "gossip_peers" in metrics_text
            and 'outcome="ok"' in metrics_text
        )
        # Survivor honors the serving preemption contract.
        b.proc.send_signal(signal.SIGTERM)
        rc_b = b.proc.wait(240)
        gw.stop()
        observer.close()
    finally:
        for h in hosts:
            h.kill()
    print(json.dumps({
        "accepted": len(accepted), "completed": len(results),
        "errors": errors, "failed": s["failed"],
        "retries": s["retries"], "quarantines": s["quarantines"],
        "gossip_dead": bool(gossip_dead),
        "post_kill_hosts": post_kill_hosts,
        "survivor_exit": rc_b,
        "metrics_clean": metrics_clean,
    }))
    assert not errors, f"accepted requests lost: {errors}"
    assert len(results) == len(accepted)
    assert s["failed"] == 0, s
    assert quarantined, "gateway never quarantined the killed host"
    assert gossip_dead, "gossip never flagged the killed host dead"
    assert post_kill_hosts == ["hostB"], (
        f"post-kill traffic not rebalanced onto the survivor: "
        f"{post_kill_hosts}"
    )
    assert rc_b == RESUMABLE_EXIT_CODE, (
        f"survivor drain exit {rc_b} != {RESUMABLE_EXIT_CODE}"
    )
    assert metrics_clean, "gateway metrics did not scrape clean"
    return 0


def child_host_partition_main() -> int:
    """SIGSTOP a host (alive but silent — a partition, not a crash):
    gossip ages it suspect -> dead, the gateway fences it, traffic
    completes on the peer; SIGCONT heals and the probe loop reinstates."""
    _fleet_cpu(2)
    import numpy as np
    from mx_rcnn_tpu.serve import GatewayRouter, GossipNode
    from mx_rcnn_tpu.serve.gossip import ALIVE as G_ALIVE
    from mx_rcnn_tpu.serve.gossip import DEAD as G_DEAD

    wd = _fabric_workdir()
    hosts: list[_FabricHost] = []
    try:
        a = _FabricHost(wd, "hostA", replicas=2, seed=0)
        hosts.append(a)
        a.wait_ready(600)
        b = _FabricHost(wd, "hostB", replicas=2, seed=0,
                        peers=f"hostA={a.addr}")
        hosts.append(b)
        b.wait_ready(600)

        observer = GossipNode(
            "chaos-observer", "", lambda: {"draining": True},
            peers={"hostA": a.addr, "hostB": b.addr},
            period_s=0.25, suspect_after_s=1.0, dead_after_s=3.0,
        ).start()
        gw = GatewayRouter(
            [a.addr, b.addr], probe_interval_s=0.25, gossip=observer,
        ).start()
        assert wait_for(lambda: gw.stats()["replicas"] == 2, 120), (
            f"gateway never saw both hosts routable: {gw.stats()}"
        )
        os.kill(a.proc.pid, signal.SIGSTOP)  # silent, not dead
        partition_detected = wait_for(
            lambda: (
                observer.peers().get("hostA") is not None
                and observer.peers()["hostA"].status == G_DEAD
            ),
            60,
        )
        fenced = wait_for(
            lambda: gw.stats()["hosts"]
            .get("hostA", {}).get("state") != "ready",
            60,
        )
        img = np.random.default_rng(0).uniform(
            0, 255, (100, 100, 3)
        ).astype(np.float32)
        accepted = [gw.submit(img, timeout=120) for _ in range(6)]
        results, errors = _collect_results(accepted)
        during = sorted({r["host_id"] for r in results})
        os.kill(a.proc.pid, signal.SIGCONT)  # partition heals
        healed = wait_for(
            lambda: (
                observer.peers().get("hostA") is not None
                and observer.peers()["hostA"].status == G_ALIVE
            ),
            120,
        )
        reinstated = wait_for(
            lambda: gw.stats()["hosts"]
            .get("hostA", {}).get("state") == "ready",
            120,
        )
        s = gw.stats()
        gw.stop()
        observer.close()
    finally:
        for h in hosts:
            try:
                os.kill(h.proc.pid, signal.SIGCONT)  # un-freeze first
            except OSError:
                pass
            h.kill()
    print(json.dumps({
        "accepted": len(accepted), "completed": len(results),
        "errors": errors, "failed": s["failed"],
        "partition_detected": bool(partition_detected),
        "fenced": bool(fenced), "hosts_during_partition": during,
        "healed": bool(healed), "reinstated": bool(reinstated),
        "quarantines": s["quarantines"],
        "reinstatements": s["reinstatements"],
        "routable_final": s["replicas"],
    }))
    assert partition_detected, "gossip never aged the stopped host to dead"
    assert fenced, "gateway kept routing to the partitioned host"
    assert not errors and len(results) == len(accepted), (
        f"requests lost during the partition: {errors}"
    )
    assert during == ["hostB"], (
        f"partitioned host served traffic while fenced: {during}"
    )
    assert healed, "gossip never saw the host come back alive"
    assert reinstated, "probe loop never reinstated the healed host"
    assert s["replicas"] == 2, s
    assert s["failed"] == 0, s
    return 0


def child_cross_host_swap_main() -> int:
    """Pod-wide generation-tagged weight roll across two REAL host
    processes under load: every response from either host must
    bitwise-match the oracle for the generation it reports."""
    _fleet_cpu(2)
    import numpy as np
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.serve import GatewayRouter

    cfg = get_config(CONFIG)
    v1 = _init_variables(cfg, seed=1)  # the roll target
    wd = _fabric_workdir()
    hosts: list[_FabricHost] = []
    KEYS = ("boxes", "scores", "classes")

    def sig(res):
        return {k: np.asarray(res[k]) for k in KEYS}

    def matches(res, oracle) -> bool:
        return all(
            np.array_equal(np.asarray(res[k]), oracle[k]) for k in KEYS
        )

    try:
        a = _FabricHost(wd, "hostA", replicas=2, seed=0)
        hosts.append(a)
        a.wait_ready(600)
        b = _FabricHost(wd, "hostB", replicas=2, seed=0,
                        peers=f"hostA={a.addr}")
        hosts.append(b)
        b.wait_ready(600)
        gw = GatewayRouter([a.addr, b.addr], probe_interval_s=0.25).start()
        assert wait_for(lambda: gw.stats()["replicas"] == 2, 120), (
            f"gateway never saw both hosts routable: {gw.stats()}"
        )
        probe = np.random.default_rng(7).uniform(
            0, 255, (96, 128, 3)
        ).astype(np.float32)
        # Generation-0 oracle — computed on whichever host the gateway
        # picks; every gen-0 response from EITHER host must match it
        # bitwise (hosts share seed, config and compiled program).
        oracle = {0: sig(gw.infer(probe, timeout=300))}
        results: list[dict] = []
        errors: list[str] = []
        stop = threading.Event()

        def pump() -> None:
            while not stop.is_set():
                try:
                    results.append(gw.infer(probe, timeout=300))
                except Exception as e:  # noqa: BLE001 - report, don't die
                    errors.append(f"{type(e).__name__}: {e}")
                    return

        pumps = [
            threading.Thread(target=pump, daemon=True) for _ in range(2)
        ]
        for t in pumps:
            t.start()
        wait_for(lambda: len(results) >= 2, 300)
        gen = gw.swap_weights(v1)  # hosts rolled ONE AT A TIME
        wait_for(
            lambda: any(
                r.get("generation") == gen for r in list(results)
            ),
            300,
        )
        stop.set()
        for t in pumps:
            t.join(300)
        oracle[gen] = sig(gw.infer(probe, timeout=300))
        s = gw.stats()
        gw.stop()
    finally:
        for h in hosts:
            h.kill()
    gens = sorted({r["generation"] for r in results})
    hosts_used = sorted({r["host_id"] for r in results})
    mismatched = [
        i for i, r in enumerate(results)
        if r["generation"] not in oracle
        or not matches(r, oracle[r["generation"]])
    ]
    print(json.dumps({
        "responses": len(results), "generations_seen": gens,
        "hosts_used": hosts_used, "mismatched": mismatched,
        "errors": errors, "swap_generation": gen,
        "host_generations": {
            h: d["generation"] for h, d in s["hosts"].items()
        },
    }))
    assert not errors, f"requests failed during the roll: {errors}"
    assert gens == [0, gen], (
        f"expected traffic on both sides of the roll, saw {gens}"
    )
    assert hosts_used == ["hostA", "hostB"], (
        f"oracle only exercised one host: {hosts_used}"
    )
    assert not mismatched, (
        f"{len(mismatched)} responses matched NEITHER generation oracle "
        "— a host served mixed or stale weights"
    )
    return 0


# -- continuous-deployment scenarios (ctrl/deploy.py) -------------------------


def _deploy_runner_cls():
    """Weight-sensitive runner-protocol fake for the deploy children
    (mirrors tools/soak.py::_SoakRunner — kept separate so the tool
    never imports the test suite).  Every detection carries a signature
    derived from the currently-loaded tree, so bitwise response parity
    across engines holds if and only if their weights are bitwise
    equal."""
    import numpy as np

    class _WeightRunner:
        def __init__(self, variables, delay: float = 0.002):
            self.buckets = [(64, 64)]
            self.batch_size = 1
            self.delay = delay
            self.generation = 0
            self.swapped: list = []
            self._warmed = set()
            self._sig = self._sig_of(variables)

        @staticmethod
        def _sig_of(tree) -> float:
            leaves: list = []

            def walk(x):
                if isinstance(x, dict):
                    for k in sorted(x):
                        walk(x[k])
                else:
                    leaves.append(np.asarray(x))

            walk(tree)
            return float(np.ravel(leaves[0])[0]) if leaves else 0.0

        def levels(self):
            return ("full", "reduced", "proposals")

        def pick_bucket(self, h, w):
            return self.buckets[0]

        def smaller_bucket(self, bucket):
            return None

        def warmup(self):
            for b in self.buckets:
                for mode in self.levels():
                    self._warmed.add((mode, b))
            return len(self._warmed)

        def swap_weights(self, variables, generation=None):
            gen = (self.generation + 1 if generation is None
                   else int(generation))
            if gen <= self.generation:
                raise ValueError("generation must be monotonic")
            self.generation = gen
            self._sig = self._sig_of(variables)
            self.swapped.append((gen, variables))
            return gen

        def run(self, mode, bucket, images):
            assert (mode, tuple(bucket)) in self._warmed, (
                f"RECOMPILATION on serving path: {(mode, bucket)}"
            )
            if self.delay:
                time.sleep(self.delay)
            s = self._sig
            return [
                {
                    "boxes": np.array(
                        [[0.0, 0.0, 1.0 + s, 1.0 + s]], np.float32
                    ),
                    "scores": np.array([0.9], np.float32),
                    "classes": np.zeros(1, np.int32),
                    "generation": self.generation,
                }
                for _ in images
            ]

    return _WeightRunner


def _deploy_fleet(live_tree, delay: float = 0.002):
    """(fleet, live-runner dict) over weight-sensitive fakes.  The
    returned dict holds ONLY the in-rotation replicas — the Deployer's
    spare canary engine reuses the same factory under a later rid, and
    its swaps must never count as fleet rolls."""
    from mx_rcnn_tpu.serve import FleetRouter, InferenceEngine

    WeightRunner = _deploy_runner_cls()
    n = 2
    runners: dict = {}

    def factory(rid: int) -> InferenceEngine:
        r = WeightRunner(live_tree, delay=delay)
        runners[rid] = r
        return InferenceEngine(r, replica_id=rid, hang_timeout=60.0)

    fleet = FleetRouter(
        factory, n, supervisor_poll=0.1, initial_weights=live_tree,
    )
    return fleet, runners, n


def child_deploy_reject_main() -> int:
    """Two poisoned candidates land under live traffic: a corrupt
    checkpoint (bit-flipped after its manifest was written) and a
    healthy-on-disk tree whose detections regress on the golden set.
    Both must be rejected — and no served response may EVER carry a
    candidate generation tag (rejected generations are burned)."""
    _hermetic_cpu()
    import numpy as np
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.ctrl import Deployer
    from mx_rcnn_tpu.train import checkpoint

    obs_dir = os.environ.get("MX_RCNN_OBS_DIR")
    if obs_dir:
        obs.configure(obs_dir)

    live_tree = {"w": np.full((8,), 3.0, np.float32)}
    bad_tree = {"w": np.full((8,), 40.0, np.float32)}
    fleet, runners, n_live = _deploy_fleet(live_tree)

    ckpt_dir = tempfile.mkdtemp(prefix="mx_rcnn_deploy_reject_")
    # Step 1: a clean save, then one flipped byte in the landed files —
    # the manifest checksum must refuse it BEFORE deserialization.
    checkpoint.save_checkpoint(
        ckpt_dir, {"step": 1, "variables": bad_tree}, manifest=True
    )
    manifest = checkpoint.read_manifest(ckpt_dir, 1)
    rel = max(manifest["files"], key=lambda r: manifest["files"][r]["bytes"])
    blob = os.path.join(checkpoint._step_dir(ckpt_dir, 1), rel)
    with open(blob, "r+b") as f:
        raw = bytearray(f.read())
        raw[len(raw) // 2] ^= 0xFF
        f.seek(0)
        f.write(bytes(raw))
    # Step 2: restores fine, but every detection moves away from the
    # live tree's golden ground truth (parity fails AND mAP regresses).
    checkpoint.save_checkpoint(
        ckpt_dir, {"step": 2, "variables": bad_tree}, manifest=True
    )

    live_sig = 3.0
    golden = {
        "images": [np.zeros((32, 32, 3), np.float32)],
        "gt": {0: {"0": {
            "boxes": np.array(
                [[0.0, 0.0, 1.0 + live_sig, 1.0 + live_sig]], np.float32
            ),
            "difficult": np.zeros(1, bool),
        }}},
    }

    served: list = []
    errors: list = []
    stop = threading.Event()

    def pump() -> None:
        i = 0
        while not stop.is_set():
            img = np.full((32, 32, 3), float(i % 13), np.float32)
            try:
                served.append(fleet.infer(img, timeout=60))
            except Exception as e:  # noqa: BLE001 - report, don't die
                errors.append(f"{type(e).__name__}: {e}")
                return
            i += 1
            time.sleep(0.004)

    with fleet:
        dep = Deployer(
            fleet, ckpt_dir,
            mirror_rate=1.0, min_mirrored=5, shadow_window_s=30.0,
            mirror_timeout_s=15.0, slo_fast_s=2.0, slo_slow_s=6.0,
            watch_window_s=30.0, golden=golden,
        )
        pumps = [
            threading.Thread(target=pump, daemon=True) for _ in range(2)
        ]
        for t in pumps:
            t.start()
        wait_for(lambda: len(served) >= 5, 120)
        decisions = dep.step_once()
        stop.set()
        for t in pumps:
            t.join(60)

    burned = sorted(
        h["generation"] for h in dep.history
        if h["kind"] == "deploy_shadow_start"
    )
    gens_served = sorted({r["generation"] for r in served})
    leaked = [g for g in gens_served if g in burned]
    print(json.dumps({
        "decisions": [
            {"step": d["step"], "outcome": d["outcome"],
             "reason": d.get("reason")}
            for d in decisions
        ],
        "responses": len(served),
        "generations_served": gens_served,
        "candidate_generations": burned,
        "leaked_generations": leaked,
        "fleet_generation": fleet.generation,
        "live_swaps": sum(
            len(runners[rid].swapped) for rid in range(n_live)
        ),
        "errors": errors,
    }))
    assert not errors, f"live requests failed during rejection: {errors}"
    assert len(decisions) == 2, decisions
    assert decisions[0]["outcome"] == "invalid", decisions[0]
    assert decisions[0]["reason"].startswith("file_checksum_mismatch"), \
        decisions[0]
    assert decisions[1]["outcome"] == "rejected", decisions[1]
    assert decisions[1]["reason"] == "parity", decisions[1]
    assert fleet.generation == 0, fleet.generation
    assert all(not runners[rid].swapped for rid in range(n_live)), (
        "a live replica was swapped despite both candidates failing the gate"
    )
    assert served and gens_served == [0], gens_served
    assert not leaked, (
        f"rejected candidate generation(s) {leaked} appeared in served "
        "responses"
    )
    return 0


def child_deploy_rollback_main() -> int:
    """Promote a parity-clean candidate, then inject latency so the
    LIVE SLO burns inside the post-promote watch window: the Deployer
    must automatically re-publish the previous generation's retained
    tree — bitwise — under a NEW, HIGHER generation number, landing the
    whole fleet back on a single generation."""
    _hermetic_cpu()
    import numpy as np
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import CtrlConfig
    from mx_rcnn_tpu.ctrl import Deployer, SLOEngine, default_slos
    from mx_rcnn_tpu.train import checkpoint

    obs_dir = os.environ.get("MX_RCNN_OBS_DIR")
    if obs_dir:
        obs.configure(obs_dir)

    live_tree = {"w": np.full((8,), 3.0, np.float32)}
    # Bitwise-equal weights under a fresh step: parity passes, the
    # regression is an SLO burn AFTER promotion, not an accuracy drop.
    cand_tree = {"w": np.full((8,), 3.0, np.float32)}
    fleet, runners, n_live = _deploy_fleet(live_tree)

    ckpt_dir = tempfile.mkdtemp(prefix="mx_rcnn_deploy_rollback_")
    checkpoint.save_checkpoint(
        ckpt_dir, {"step": 1, "variables": cand_tree}, manifest=True
    )

    ctrl = CtrlConfig(latency_target=0.9, latency_threshold_s=0.05)
    live_slo = SLOEngine(
        default_slos(ctrl), fast_s=2.0, slow_s=6.0, burn_factor=2.0,
    ).start(0.2)

    served: list = []
    errors: list = []
    stop = threading.Event()

    def pump() -> None:
        i = 0
        while not stop.is_set():
            img = np.full((32, 32, 3), float(i % 13), np.float32)
            try:
                served.append(fleet.infer(img, timeout=60))
            except Exception as e:  # noqa: BLE001 - report, don't die
                errors.append(f"{type(e).__name__}: {e}")
                return
            i += 1
            time.sleep(0.004)

    rollback = None
    try:
        with fleet:
            # Shadow-scoped availability is relaxed: the spare engine's
            # bounded queue can shed a burst under the 1.0 mirror rate,
            # and a single shed in ~15 samples would fail a 0.95 target
            # — this scenario's regression is the post-promote LIVE
            # latency burn, not shadow capacity.
            dep = Deployer(
                fleet, ckpt_dir,
                mirror_rate=1.0, min_mirrored=5, shadow_window_s=30.0,
                mirror_timeout_s=15.0, slo_fast_s=2.0, slo_slow_s=6.0,
                watch_window_s=120.0, live_slo=live_slo,
                availability_target=0.5,
            )
            pumps = [
                threading.Thread(target=pump, daemon=True)
                for _ in range(2)
            ]
            for t in pumps:
                t.start()
            wait_for(lambda: len(served) >= 5, 120)
            decisions = dep.step_once()
            assert decisions and decisions[-1]["outcome"] == "promoted", \
                decisions
            promoted_gen = decisions[-1]["generation"]
            wait_for(
                lambda: any(
                    r["generation"] == promoted_gen for r in list(served)
                ),
                120,
            )
            # The new generation misbehaves in production: every live
            # request now lands far above the latency SLO threshold.
            for rid in range(n_live):
                runners[rid].delay = 0.3
            deadline = time.monotonic() + 90
            while rollback is None and time.monotonic() < deadline:
                for d in dep.step_once():
                    if d["outcome"] == "rolled_back":
                        rollback = d
                time.sleep(0.2)
            for rid in range(n_live):
                runners[rid].delay = 0.002  # lift so the drain is quick
            stop.set()
            for t in pumps:
                t.join(60)
            wait_for(
                lambda: rollback is not None and any(
                    r[0] == rollback["to_generation"]
                    for rid in range(n_live)
                    for r in runners[rid].swapped
                ),
                60,
            )
    finally:
        live_slo.stop()

    assert rollback is not None, (
        "live SLO burn inside the watch window never triggered rollback"
    )
    restored = [runners[rid].swapped[-1] for rid in range(n_live)]
    bitwise = all(
        gen == rollback["to_generation"]
        and sorted(tree) == sorted(live_tree)
        and all(np.array_equal(tree[k], live_tree[k]) for k in tree)
        for gen, tree in restored
    )
    pod_gens = sorted({runners[rid].generation for rid in range(n_live)})
    gens_served = sorted({r["generation"] for r in served})
    print(json.dumps({
        "promoted_generation": promoted_gen,
        "from_generation": rollback["from_generation"],
        "to_generation": rollback["to_generation"],
        "restored_generation": rollback["restored_generation"],
        "burn_slo": rollback["slo"],
        "bitwise_restore": bitwise,
        "pod_generations": pod_gens,
        "generations_served": gens_served,
        "responses": len(served),
        "errors": errors,
    }))
    assert not errors, f"live requests failed during the roll: {errors}"
    assert rollback["from_generation"] == promoted_gen, rollback
    assert rollback["to_generation"] > promoted_gen, (
        "rollback rewound the generation number: "
        f"{rollback['to_generation']} <= {promoted_gen}"
    )
    assert fleet.generation == rollback["to_generation"], fleet.generation
    assert bitwise, (
        "rollback did not restore the previous generation's tree bitwise"
    )
    assert pod_gens == [rollback["to_generation"]], (
        f"pod split across generations after rollback: {pod_gens}"
    )
    assert set(gens_served) <= {0, promoted_gen,
                                rollback["to_generation"]}, gens_served
    return 0


def compare_main(dir_a: str, dir_b: str) -> int:
    """Bitwise-compare the newest checkpoints of two run dirs."""
    _hermetic_cpu()
    import numpy as np

    import jax
    from mx_rcnn_tpu.train.checkpoint import restore_raw

    a, b = restore_raw(dir_a), restore_raw(dir_b)
    fa, ta = jax.tree_util.tree_flatten(a)
    fb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb:
        print(json.dumps({"equal": False, "why": "tree structure differs"}))
        return 1
    diffs = [
        i for i, (x, y) in enumerate(zip(fa, fb))
        if not np.array_equal(np.asarray(x), np.asarray(y))
    ]
    print(json.dumps({"equal": not diffs, "leaves": len(fa), "diffs": diffs}))
    return 1 if diffs else 0


# -- orchestrator -------------------------------------------------------------


def train_argv(workdir: str, steps: int, resume: bool = False,
               cache_dir: str | None = None, service_workers: int = 2,
               respawns: int = 2,
               extra_sets: tuple[str, ...] = ()) -> list[str]:
    # Every train child runs the PRODUCTION input path: process decode
    # workers + the checksummed tensor cache.  The cache root is shared
    # across sibling scenarios by default (one level above the per-
    # scenario workdir): baseline populates it cold, sigkill/sigterm/
    # truncate resume against it warm — so the standing bit-identity
    # comparisons double as the cache-hit-vs-miss bitwise proof.
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(workdir)), "tensor_cache"
        )
    argv = [
        sys.executable, os.path.abspath(__file__), "--child", "--",
        "--config", CONFIG, "--workdir", workdir,
        "--steps", str(steps), "--no-eval",
        "--set", f"train.checkpoint_every={CKPT_EVERY}",
        "--set", f"train.log_every={LOG_EVERY}",
        "--set", f"data.num_workers={service_workers}",
        "--set", f"data.worker_respawns={respawns}",
        "--set", f"data.cache_dir={cache_dir}",
    ]
    for item in extra_sets:
        argv += ["--set", item]
    if resume:
        argv.append("--resume")
    return argv


def eval_argv(workdir: str, ckpt: str, resume: bool = False) -> list[str]:
    argv = [
        sys.executable, os.path.abspath(__file__), "--child-eval", "--",
        "--config", CONFIG, "--workdir", workdir, "--ckpt", ckpt,
        "--resumable", "--shard-size", "1", "--limit", str(EVAL_LIMIT),
        "--dump", os.path.join(workdir, "detections.json"),
    ]
    if resume:
        argv.append("--resume")
    return argv


def ckpt_dir(workdir: str) -> str:
    return os.path.join(workdir, CONFIG, "ckpt")


def finalized_steps(workdir: str) -> list[int]:
    """Finalized orbax step dirs (bare ints; tmp dirs have suffixes)."""
    d = ckpt_dir(workdir)
    if not os.path.isdir(d):
        return []
    return sorted(
        int(n) for n in os.listdir(d)
        if n.isdigit() and os.path.isdir(os.path.join(d, n))
    )


def metrics_rows(workdir: str) -> list[dict]:
    path = os.path.join(workdir, CONFIG, "metrics.jsonl")
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    return rows


# Every live chaos subprocess, so a scenario-timeout (or harness exit)
# can SIGKILL the lot instead of leaving orphans holding the CI budget.
_LIVE_PROCS: set = set()


def reap_orphans() -> int:
    """SIGKILL every still-live chaos child; returns how many."""
    reaped = 0
    for proc in list(_LIVE_PROCS):
        if proc.poll() is None:
            try:
                proc.kill()
                proc.wait(5)
                reaped += 1
            except Exception:  # noqa: BLE001 - best effort by design
                pass
        _LIVE_PROCS.discard(proc)
    return reaped


class Child:
    def __init__(self, workdir: str, argv: list[str],
                 log_name: str = "child-first",
                 env: dict | None = None) -> None:
        self.log_path = os.path.join(workdir, f"{log_name}.log")
        os.makedirs(workdir, exist_ok=True)
        self._log = open(self.log_path, "a")
        self.proc = subprocess.Popen(
            argv,
            stdout=self._log, stderr=subprocess.STDOUT,
            env={**os.environ, **(env or {})}, cwd=REPO_ROOT,
        )
        _LIVE_PROCS.add(self.proc)

    def wait(self, timeout: float) -> int:
        try:
            return self.proc.wait(timeout)
        finally:
            self._log.close()
            _LIVE_PROCS.discard(self.proc)

    def signal(self, sig: int) -> None:
        self.proc.send_signal(sig)

    def log_tail(self, n: int = 30) -> str:
        with open(self.log_path) as f:
            return "".join(f.readlines()[-n:])

    def log_contains(self, needle: str) -> bool:
        try:
            with open(self.log_path) as f:
                return needle in f.read()
        except OSError:
            return False


def wait_for(predicate, timeout: float, poll: float = 0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(poll)
    return None


def run_argv_to_completion(workdir: str, argv: list[str], timeout: float,
                           log_name: str, env: dict | None = None) -> int:
    child = Child(workdir, argv, log_name=log_name, env=env)
    rc = child.wait(timeout)
    if rc not in (0,):
        raise AssertionError(
            f"child exited {rc} (log: {child.log_path})\n{child.log_tail()}"
        )
    return rc


def run_to_completion(workdir: str, steps: int, timeout: float,
                      resume: bool = False, env: dict | None = None,
                      **argv_kw) -> int:
    return run_argv_to_completion(
        workdir, train_argv(workdir, steps, resume, **argv_kw), timeout,
        log_name=f"child-{'resume' if resume else 'first'}", env=env,
    )


def bitwise_equal(workdir_a: str, workdir_b: str, timeout: float) -> bool:
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--compare",
         ckpt_dir(workdir_a), ckpt_dir(workdir_b)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO_ROOT,
    )
    sys.stderr.write(out.stdout + out.stderr)
    return out.returncode == 0


def interrupt_at_checkpoint(workdir: str, steps: int, sig: int,
                            min_step: int, timeout: float) -> int:
    """Start a run, deliver ``sig`` once a checkpoint >= min_step is
    finalized, return the exit code."""
    child = Child(workdir, train_argv(workdir, steps))
    hit = wait_for(
        lambda: [s for s in finalized_steps(workdir) if s >= min_step],
        timeout,
    )
    if not hit:
        child.signal(signal.SIGKILL)
        child.wait(timeout)
        raise AssertionError(
            f"no checkpoint >= {min_step} appeared within {timeout}s "
            f"(log: {child.log_path})\n{child.log_tail()}"
        )
    child.signal(sig)
    return child.wait(timeout)


def scenario_baseline(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "baseline")
    done = finalized_steps(wd)
    if done and done[-1] == steps:  # idempotent across partial reruns
        return {"final_step": steps, "reused": True}
    run_to_completion(wd, steps, timeout)
    final = finalized_steps(wd)
    assert final and final[-1] == steps, f"final checkpoints: {final}"
    return {"final_step": final[-1]}


def scenario_sigkill(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "sigkill")
    rc = interrupt_at_checkpoint(
        wd, steps, signal.SIGKILL, min_step=CKPT_EVERY, timeout=timeout
    )
    assert rc == -signal.SIGKILL, f"expected SIGKILL death, got rc={rc}"
    interrupted_at = finalized_steps(wd)[-1]
    assert interrupted_at < steps, "child finished before the kill landed"
    run_to_completion(wd, steps, timeout, resume=True)
    assert finalized_steps(wd)[-1] == steps
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "resumed-after-SIGKILL params differ from the uninterrupted run"
    )
    return {"killed_after_step": interrupted_at, "bit_identical": True}


def scenario_sigterm(root: str, steps: int, timeout: float) -> dict:
    # Pinned contract (EX_TEMPFAIL) — mirrored from train/preemption.py so
    # the orchestrator stays import-free; test_robustness pins the value.
    RESUMABLE_EXIT_CODE = 75

    wd = os.path.join(root, "sigterm")
    rc = interrupt_at_checkpoint(
        wd, steps, signal.SIGTERM, min_step=CKPT_EVERY, timeout=timeout
    )
    assert rc == RESUMABLE_EXIT_CODE, (
        f"expected resumable exit {RESUMABLE_EXIT_CODE}, got {rc}"
    )
    emergency = finalized_steps(wd)[-1]
    assert emergency < steps
    run_to_completion(wd, steps, timeout, resume=True)
    assert finalized_steps(wd)[-1] == steps
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "resumed-after-SIGTERM params differ from the uninterrupted run"
    )
    return {"emergency_step": emergency, "bit_identical": True}


def scenario_nan(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "nan")
    poison = CKPT_EVERY + 2  # inside the second checkpoint interval
    run_to_completion(
        wd, steps, timeout, env={"MX_RCNN_CHAOS_NAN_STEPS": str(poison)}
    )
    assert finalized_steps(wd)[-1] == steps
    rows = metrics_rows(wd)
    assert rows and rows[-1]["step"] == steps, f"metrics rows: {rows}"
    bad = [
        (r["step"], k) for r in rows for k, v in r.items()
        if isinstance(v, float) and v != v  # NaN
    ]
    assert not bad, f"non-finite metrics survived the rollback: {bad}"
    return {"poisoned_batch": poison, "metric_rows": len(rows)}


def scenario_truncate(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "truncate")
    rc = interrupt_at_checkpoint(
        wd, steps, signal.SIGKILL, min_step=2 * CKPT_EVERY, timeout=timeout
    )
    assert rc == -signal.SIGKILL
    latest = finalized_steps(wd)[-1]
    # Truncate every file of the newest checkpoint — a kill mid-write.
    clipped = 0
    for dirpath, _, files in os.walk(os.path.join(ckpt_dir(wd), str(latest))):
        for name in files:
            path = os.path.join(dirpath, name)
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(size // 2)
            clipped += 1
    assert clipped, f"checkpoint step {latest} has no files to truncate"
    run_to_completion(wd, steps, timeout, resume=True)
    assert finalized_steps(wd)[-1] == steps
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "recovery past the truncated checkpoint lost bit-identity"
    )
    return {"truncated_step": latest, "files_clipped": clipped,
            "bit_identical": True}


# -- data-path scenarios ------------------------------------------------------


def _child_log(workdir: str, name: str = "child-first") -> str:
    try:
        with open(os.path.join(workdir, f"{name}.log")) as f:
            return f.read()
    except OSError:
        return ""


def scenario_data_worker_kill(root: str, steps: int, timeout: float) -> dict:
    """SIGKILL one decode worker mid-epoch (the worker self-kills on a
    claimed batch index); its in-flight batches are reassigned and the
    final params must be bitwise-identical to the uninterrupted run."""
    wd = os.path.join(root, "data_worker_kill")
    os.makedirs(wd, exist_ok=True)
    sentinel = os.path.join(wd, "suicide.sentinel")
    obs_dir = os.path.join(wd, "obs")
    kill_idx = CKPT_EVERY + 1  # mid-epoch, past the first checkpoint
    run_to_completion(
        wd, steps, timeout,
        env={"MX_RCNN_CHAOS_DATA_SUICIDE": f"{kill_idx}:{sentinel}"},
        extra_sets=("obs.enabled=true", f"obs.dir={obs_dir}"),
    )
    assert finalized_steps(wd)[-1] == steps
    assert os.path.exists(sentinel), (
        "no worker ever claimed the suicide fault — the service path "
        "did not run"
    )
    logtxt = _child_log(wd)
    assert "chaos: self-SIGKILL" in logtxt, "worker never self-killed"
    assert "respawning" in logtxt, (
        "dead worker was never respawned (watchdog missed the death)"
    )
    # The grep strings above are derived from the typed journal — the
    # same death must be queryable as a worker_death event with payload.
    sys.path.insert(0, REPO_ROOT)
    try:
        from mx_rcnn_tpu.obs import read_journal
    finally:
        sys.path.pop(0)

    journal = read_journal(os.path.join(obs_dir, "journal.jsonl"))
    deaths = [r for r in journal if r.get("kind") == "worker_death"]
    assert deaths, "journal recorded no worker_death event"
    assert any(
        r.get("kind") == "checkpoint_saved" for r in journal
    ), "journal recorded no checkpoint_saved event"
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "params diverged after a decode-worker SIGKILL — reassignment "
        "is not schedule-deterministic"
    )
    return {"killed_batch": kill_idx, "bit_identical": True,
            "journal_events": len(journal)}


def scenario_data_worker_wedge(root: str, steps: int, timeout: float) -> dict:
    """One worker wedges (sleeps without heartbeating); the tightened
    watchdog must reap + respawn it, the run completes bit-identical, and
    per-interval data_stall_ms stays bounded by the watchdog — the wedge
    sleep itself (3600s) must never leak into the consumer wait."""
    wd = os.path.join(root, "data_worker_wedge")
    os.makedirs(wd, exist_ok=True)
    sentinel = os.path.join(wd, "wedge.sentinel")
    wedge_idx = CKPT_EVERY + 1
    watchdog_s = 4.0
    run_to_completion(
        wd, steps, timeout,
        env={
            "MX_RCNN_CHAOS_DATA_WEDGE": f"{wedge_idx}:{sentinel}",
            "MX_RCNN_DATA_WATCHDOG_S": str(watchdog_s),
        },
    )
    assert finalized_steps(wd)[-1] == steps
    assert os.path.exists(sentinel), "no worker ever claimed the wedge"
    logtxt = _child_log(wd)
    assert "wedged" in logtxt, "watchdog never reaped the wedged worker"
    assert "respawning" in logtxt
    stalls = [
        r["data_stall_ms"] for r in metrics_rows(wd)
        if "data_stall_ms" in r
    ]
    assert stalls, "no data_stall_ms rows — stall metering is dark"
    bound_ms = 30_000.0  # generous: watchdog 4s + respawn + CPU decode
    assert max(stalls) < bound_ms, (
        f"data_stall_ms peaked at {max(stalls):.0f}ms — the wedge leaked "
        f"past the {watchdog_s:.0f}s watchdog"
    )
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "params diverged after a wedged decode worker"
    )
    return {"wedged_batch": wedge_idx, "max_stall_ms": round(max(stalls), 1),
            "bit_identical": True}


def _blob_valid(path: str) -> bool:
    """Inline tensor-blob integrity check (mirrors data/cache.py's layout:
    magic, u32 header len, JSON header with crc32/nbytes, payload) — the
    orchestrator stays package-import-free."""
    import struct
    import zlib

    with open(path, "rb") as f:
        blob = f.read()
    magic = b"MXTC1\n"
    if not blob.startswith(magic) or len(blob) < len(magic) + 4:
        return False
    (hlen,) = struct.unpack_from("<I", blob, len(magic))
    try:
        header = json.loads(blob[len(magic) + 4 : len(magic) + 4 + hlen])
    except ValueError:
        return False
    payload = blob[len(magic) + 4 + hlen :]
    return (
        len(payload) == header["nbytes"]
        and zlib.crc32(payload) == header["crc32"]
    )


def scenario_cache_corrupt(root: str, steps: int, timeout: float) -> dict:
    """Bit-rot a cached tensor blob between two runs sharing the cache:
    the second run must detect the checksum mismatch, quarantine + rebuild
    the blob, complete, and stay bitwise-identical to baseline — corrupt
    cache bytes are never served."""
    import glob as _glob

    wd = os.path.join(root, "cache_corrupt")
    cache = os.path.join(wd, "tensor_cache")  # private: we poison it
    wd_a = os.path.join(wd, "populate")
    run_to_completion(wd_a, steps, timeout, cache_dir=cache)
    assert finalized_steps(wd_a)[-1] == steps
    blobs = sorted(_glob.glob(os.path.join(cache, "tensors", "*", "*.blob")))
    assert blobs, f"populate run wrote no tensor blobs under {cache}"
    victim = blobs[0]
    with open(victim, "r+b") as f:
        f.seek(-8, os.SEEK_END)
        tail = f.read(8)
        f.seek(-8, os.SEEK_END)
        f.write(bytes(b ^ 0xFF for b in tail))  # flip payload bytes
    assert not _blob_valid(victim), "corruption did not take"
    wd_b = os.path.join(wd, "repair")
    run_to_completion(wd_b, steps, timeout, cache_dir=cache)
    assert finalized_steps(wd_b)[-1] == steps
    qpath = os.path.join(wd_b, CONFIG, "quarantine.jsonl")
    assert os.path.exists(qpath), "corrupt blob was never quarantined"
    reasons = set()
    with open(qpath) as f:
        for line in f:
            try:
                reasons.add(json.loads(line).get("reason"))
            except ValueError:
                pass
    assert "cache_checksum" in reasons, (
        f"expected a cache_checksum quarantine record, got {sorted(reasons)}"
    )
    assert _blob_valid(victim), (
        "corrupt blob was not rebuilt in place (repair run left it rotten)"
    )
    assert bitwise_equal(os.path.join(root, "baseline"), wd_b, timeout), (
        "params diverged after cache corruption — corrupt bytes reached "
        "training"
    )
    return {"corrupted_blob": os.path.basename(victim),
            "quarantine_reasons": sorted(r for r in reasons if r),
            "bit_identical": True}


def scenario_data_service_dead(root: str, steps: int, timeout: float) -> dict:
    """Every worker dies on its first task ("always" suicide) until the
    respawn budget is exhausted; the service must degrade to in-process
    synchronous assembly and the run must STILL complete bit-identical."""
    wd = os.path.join(root, "data_service_dead")
    run_to_completion(
        wd, steps, timeout, respawns=1,
        env={"MX_RCNN_CHAOS_DATA_SUICIDE": "always"},
    )
    assert finalized_steps(wd)[-1] == steps
    logtxt = _child_log(wd)
    assert "respawn budget exhausted" in logtxt, (
        "service never exhausted its respawn budget"
    )
    assert "falling back to in-process synchronous assembly" in logtxt, (
        "service died without the logged degradation transition"
    )
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "sync-fallback params differ from the uninterrupted run"
    )
    return {"fallback": "sync", "bit_identical": True}


# -- inference scenarios ------------------------------------------------------


def shard_files(workdir: str) -> list[str]:
    d = os.path.join(workdir, CONFIG, "eval_shards")
    if not os.path.isdir(d):
        return []
    return sorted(
        n for n in os.listdir(d)
        if n.startswith("shard-") and n.endswith(".json")
    )


def _baseline_ckpt(root: str) -> str:
    d = ckpt_dir(os.path.join(root, "baseline"))
    assert os.path.isdir(d), "baseline scenario must run first"
    return d


def scenario_eval_sigkill(root: str, steps: int, timeout: float) -> dict:
    ckpt = _baseline_ckpt(root)
    ref = os.path.join(root, "eval_ref")
    run_argv_to_completion(
        ref, eval_argv(ref, ckpt), timeout, log_name="eval-ref"
    )
    with open(os.path.join(ref, "detections.json"), "rb") as f:
        ref_bytes = f.read()

    wd = os.path.join(root, "eval_sigkill")
    child = Child(wd, eval_argv(wd, ckpt), log_name="eval-first")
    hit = wait_for(lambda: shard_files(wd), timeout, poll=0.05)
    if not hit:
        child.signal(signal.SIGKILL)
        child.wait(timeout)
        raise AssertionError(
            f"no shard checkpoint appeared within {timeout}s "
            f"(log: {child.log_path})\n{child.log_tail()}"
        )
    child.signal(signal.SIGKILL)
    rc = child.wait(timeout)
    assert rc == -signal.SIGKILL, f"expected SIGKILL death, got rc={rc}"
    partial = len(shard_files(wd))
    assert 0 < partial < EVAL_LIMIT, (
        f"kill left {partial}/{EVAL_LIMIT} shards — nothing to resume"
    )
    run_argv_to_completion(
        wd, eval_argv(wd, ckpt, resume=True), timeout, log_name="eval-resume"
    )
    assert len(shard_files(wd)) == EVAL_LIMIT
    with open(os.path.join(wd, "detections.json"), "rb") as f:
        got = f.read()
    assert got == ref_bytes, (
        "resumed eval detections differ from the uninterrupted run"
    )
    return {"killed_after_shards": partial, "total_shards": EVAL_LIMIT,
            "byte_identical": True}


def scenario_eval_corrupt(root: str, steps: int, timeout: float) -> dict:
    ckpt = _baseline_ckpt(root)
    wd = os.path.join(root, "eval_corrupt")
    bad = ["3", "7"]  # inside the --limit window of the synthetic split
    run_argv_to_completion(
        wd, eval_argv(wd, ckpt), timeout, log_name="eval-corrupt",
        env={"MX_RCNN_CHAOS_BAD_IMAGES": ",".join(bad)},
    )
    qpath = os.path.join(wd, CONFIG, "quarantine.jsonl")
    assert os.path.exists(qpath), "corrupt images were not quarantined"
    with open(qpath) as f:
        rows = [json.loads(line) for line in f]
    quarantined = {str(r["image_id"]) for r in rows}
    assert set(bad) <= quarantined, (
        f"expected {bad} quarantined, got {sorted(quarantined)}"
    )
    with open(os.path.join(wd, "detections.json")) as f:
        dump = json.load(f)
    assert len(dump) == EVAL_LIMIT, (
        f"dump holds {len(dump)}/{EVAL_LIMIT} images — corrupt inputs must "
        "blank-substitute, not drop"
    )
    return {"quarantined": sorted(quarantined), "dump_images": len(dump)}


# Journal kinds written by the runtime lock sanitizer
# (mx_rcnn_tpu/analysis/lockcheck.py).  The in-process raise is the
# primary signal — a child that trips dies nonzero — but a violation on
# a thread whose exceptions get swallowed (supervisor loops, probe
# loops) still reaches the journal, and the scenario must fail on it.
SANITIZER_KINDS = {"lock_order_violation", "held_lock_blocked_call"}


def _assert_no_sanitizer_reports(wd: str) -> None:
    """Fail if any journal under this scenario's workdir carries a
    lockcheck report.  No-op when the sanitizer was not enabled."""
    if os.environ.get("MX_RCNN_LOCKCHECK") != "1":
        return
    for path in glob.glob(
        os.path.join(wd, "**", "journal.jsonl"), recursive=True
    ):
        with open(path) as f:
            for ln in f:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                assert rec.get("kind") not in SANITIZER_KINDS, (
                    f"lock sanitizer report in {path}: {rec}"
                )


def _json_child(root: str, name: str, flag: str, timeout: float,
                env: Optional[dict] = None) -> dict:
    """Run a self-asserting child mode; return its JSON stdout line."""
    wd = os.path.join(root, name)
    os.makedirs(wd, exist_ok=True)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), flag],
        capture_output=True, text=True, timeout=timeout, cwd=REPO_ROOT,
        env={**os.environ, **env} if env else None,
    )
    with open(os.path.join(wd, "child.log"), "w") as f:
        f.write(out.stdout + out.stderr)
    assert out.returncode == 0, (
        f"{name} child exited {out.returncode}:\n{out.stdout}\n{out.stderr}"
    )
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"{name} child printed no JSON:\n{out.stdout}"
    _assert_no_sanitizer_reports(wd)
    return json.loads(lines[-1])


def scenario_overload(root: str, steps: int, timeout: float) -> dict:
    r = _json_child(root, "overload", "--child-overload", timeout)
    # The child already asserted shed >= 1 and served == submitted - shed;
    # re-assert here so the summary line can't paper over a child bug.
    assert r["shed"] >= 1 and r["served"] == r["submitted"] - r["shed"], r
    return r


def scenario_hang(root: str, steps: int, timeout: float) -> dict:
    r = _json_child(root, "hang", "--child-hang", timeout)
    assert r.get("hung") == 1 and r.get("state") == "dead", r
    return r


# -- fleet scenarios ----------------------------------------------------------


def scenario_replica_kill(root: str, steps: int, timeout: float) -> dict:
    # Journal enabled: on top of the child's own zero-loss assertions,
    # the scenario proves the incident is reconstructable from the obs
    # artifacts alone (docs/observability.md).
    obs_dir = os.path.join(root, "replica_kill", "obs")
    r = _json_child(root, "replica_kill", "--child-replica-kill", timeout,
                    env={"MX_RCNN_OBS_DIR": obs_dir})
    assert r["failed"] == 0 and r["completed"] == r["accepted"], r
    assert r["quarantines"] >= 1 and r["reinstatements"] >= 1, r

    # The flight recorder fired on the kill and captured the killing
    # event in its postmortem ring.
    dumps = sorted(glob.glob(os.path.join(obs_dir, "flight_*.json")))
    assert dumps, f"no flight-recorder dump under {obs_dir}"
    dump_kinds: set = set()
    for path in dumps:
        with open(path) as f:
            dump_kinds.update(
                e.get("kind") for e in json.load(f)["entries"]
                if isinstance(e, dict)
            )
    assert "engine_killed" in dump_kinds, sorted(
        k for k in dump_kinds if k
    )

    # The journal alone reconstructs kill -> quarantine -> reinstate.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    report, _ = obs_report.build_report(obs_dir)
    tl = [e["kind"] for e in report["incident_timeline"]]
    for kind in ("engine_killed", "fleet_quarantine", "fleet_reinstate"):
        assert kind in tl, tl
    assert max(
        tl.index("engine_killed"), tl.index("fleet_quarantine")
    ) < tl.index("fleet_reinstate"), tl
    r["obs_events"] = report["journal_records"]
    r["flight_dumps"] = len(dumps)
    return r


def scenario_replica_wedge(root: str, steps: int, timeout: float) -> dict:
    r = _json_child(root, "replica_wedge", "--child-replica-wedge", timeout)
    assert r["failed"] == 0 and r["hedges"] >= 1, r
    assert r["quarantines"] >= 1 and r["p99_s"] < 60.0, r
    return r


def scenario_tenant_starvation(root: str, steps: int, timeout: float) -> dict:
    # Journal enabled: beyond the child's own isolation assertions, the
    # per-tenant story (quota rejections on the flooder, clean outcomes
    # for the victims) must be reconstructable from the obs artifacts
    # alone via tools/obs_report.py.
    obs_dir = os.path.join(root, "tenant_starvation", "obs")
    r = _json_child(root, "tenant_starvation", "--child-tenant-starvation",
                    timeout, env={"MX_RCNN_OBS_DIR": obs_dir})
    for t, v in r["victims"].items():
        assert v["completed"] == v["submitted"], (t, r)
        assert v["quota"] == 0 and v["shed"] == 0, (t, r)
    assert r["flooder"]["quota"] >= 1, r
    assert r["fleet"]["shed"] == 0 and r["fleet"]["failed"] == 0, r
    assert r["mix_p99_s"] <= 3.0 * max(r["baseline_p99_s"], 0.25), r

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    report, _ = obs_report.build_report(obs_dir)
    tenants = report["tenants"]
    assert set(tenants) >= {"victim", "bursty", "flood"}, sorted(tenants)
    assert tenants["flood"]["quota_rejections"] >= r["flooder"]["quota"], (
        tenants["flood"]
    )
    for t in ("victim", "bursty"):
        assert tenants[t]["quota_rejections"] == 0, tenants[t]
        assert tenants[t]["requests"].get("shed", 0) == 0, tenants[t]
        assert tenants[t]["requests"].get("completed", 0) >= 1, tenants[t]
    r["report_tenants"] = {
        t: {"requests": v["requests"],
            "quota_rejections": v["quota_rejections"]}
        for t, v in tenants.items()
    }
    return r


def scenario_swap_under_load(root: str, steps: int, timeout: float) -> dict:
    obs_dir = os.path.join(root, "swap_under_load", "obs")
    r = _json_child(root, "swap_under_load", "--child-swap", timeout,
                    env={"MX_RCNN_OBS_DIR": obs_dir})
    assert not r["mismatched"] and not r["errors"], r
    assert r["generations_seen"] == [0, r["swap_generation"]], r
    return r


def scenario_fleet_drain(root: str, steps: int, timeout: float) -> dict:
    """SIGTERM a real serving child mid-load; it must drain and exit 75."""
    RESUMABLE_EXIT_CODE = 75  # pinned, mirrors train/preemption.py

    wd = os.path.join(root, "fleet_drain")
    child = Child(
        wd, [sys.executable, os.path.abspath(__file__),
             "--child-fleet-drain"],
        log_name="fleet-drain",
    )
    if not wait_for(lambda: child.log_contains("FLEET_READY"), timeout):
        child.signal(signal.SIGKILL)
        child.wait(timeout)
        raise AssertionError(
            f"fleet never came up within {timeout}s "
            f"(log: {child.log_path})\n{child.log_tail()}"
        )
    time.sleep(2.0)  # let accepted load pile up mid-flight
    child.signal(signal.SIGTERM)
    rc = child.wait(timeout)
    assert rc == RESUMABLE_EXIT_CODE, (
        f"expected resumable exit {RESUMABLE_EXIT_CODE}, got {rc} "
        f"(log: {child.log_path})\n{child.log_tail()}"
    )
    with open(child.log_path) as f:
        lines = [ln for ln in f if ln.startswith("{")]
    assert lines, f"drain child printed no JSON\n{child.log_tail()}"
    r = json.loads(lines[-1])
    assert r["accepted"] > 0 and r["failed"] == 0 and r["drained_clean"], r
    _assert_no_sanitizer_reports(wd)
    return r


def scenario_fleet_scale(root: str, steps: int, timeout: float) -> dict:
    # Journal enabled: beyond the child's zero-loss assertions, the
    # scenario proves the whole resize story — decision, build, join,
    # dwell, retire — reconstructs from the obs artifacts alone.
    obs_dir = os.path.join(root, "fleet_scale", "obs")
    r = _json_child(root, "fleet_scale", "--child-fleet-scale", timeout,
                    env={"MX_RCNN_OBS_DIR": obs_dir})
    assert r["failed"] == 0 and r["completed"] == r["accepted"], r
    assert r["added"] >= 1 and r["retired"] >= 1, r

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    report, _ = obs_report.build_report(obs_dir)
    tl = [e["kind"] for e in report["incident_timeline"]]
    for kind in ("fleet_scale_up", "fleet_replica_added",
                 "fleet_scale_down", "fleet_replica_retired"):
        assert kind in tl, tl
    assert tl.index("fleet_scale_up") < tl.index("fleet_scale_down"), tl
    assert tl.index("fleet_replica_added") < tl.index(
        "fleet_replica_retired"
    ), tl
    r["obs_events"] = report["journal_records"]
    return r


# -- cross-host fabric scenarios ---------------------------------------------


def scenario_host_kill(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "host_kill")
    r = _json_child(root, "host_kill", "--child-host-kill", timeout,
                    env={"MX_RCNN_FABRIC_WD": wd})
    assert not r["errors"] and r["completed"] == r["accepted"], r
    assert r["failed"] == 0 and r["quarantines"] >= 1, r
    assert r["gossip_dead"] and r["post_kill_hosts"] == ["hostB"], r
    assert r["survivor_exit"] == 75, r
    assert r["metrics_clean"], r
    return r


def scenario_host_partition(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "host_partition")
    r = _json_child(root, "host_partition", "--child-host-partition",
                    timeout, env={"MX_RCNN_FABRIC_WD": wd})
    assert r["partition_detected"] and r["fenced"], r
    assert not r["errors"] and r["failed"] == 0, r
    assert r["hosts_during_partition"] == ["hostB"], r
    assert r["healed"] and r["reinstated"] and r["routable_final"] == 2, r
    return r


def scenario_cross_host_swap(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "cross_host_swap")
    r = _json_child(root, "cross_host_swap", "--child-cross-host-swap",
                    timeout, env={"MX_RCNN_FABRIC_WD": wd})
    assert not r["errors"] and not r["mismatched"], r
    assert r["generations_seen"] == [0, r["swap_generation"]], r
    assert r["hosts_used"] == ["hostA", "hostB"], r
    return r


# -- continuous-deployment scenarios ------------------------------------------


def _deploy_timeline(obs_dir: str) -> list:
    """Incident-timeline kinds reconstructed from the journal ALONE —
    the acceptance bar for the deploy scenarios is that the whole
    deployment story replays from the obs artifacts."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    report, _ = obs_report.build_report(obs_dir)
    return [e["kind"] for e in report["incident_timeline"]]


def scenario_deploy_reject(root: str, steps: int, timeout: float) -> dict:
    obs_dir = os.path.join(root, "deploy_reject", "obs")
    r = _json_child(root, "deploy_reject", "--child-deploy-reject", timeout,
                    env={"MX_RCNN_OBS_DIR": obs_dir})
    assert not r["errors"] and not r["leaked_generations"], r
    assert r["fleet_generation"] == 0 and r["live_swaps"] == 0, r
    assert [d["outcome"] for d in r["decisions"]] == \
        ["invalid", "rejected"], r

    tl = _deploy_timeline(obs_dir)
    assert tl.count("deploy_candidate") == 2, tl
    assert tl.count("deploy_reject") == 2, tl
    assert "deploy_promote" not in tl, tl
    # The corrupt candidate died at the manifest (no shadow); the
    # regressed one went through a full shadow verdict first.
    assert tl.count("deploy_shadow_start") == 1, tl
    assert tl.index("deploy_shadow_verdict") < \
        len(tl) - tl[::-1].index("deploy_reject"), tl
    r["timeline"] = tl
    return r


def scenario_deploy_rollback(root: str, steps: int, timeout: float) -> dict:
    obs_dir = os.path.join(root, "deploy_rollback", "obs")
    r = _json_child(root, "deploy_rollback", "--child-deploy-rollback",
                    timeout, env={"MX_RCNN_OBS_DIR": obs_dir})
    assert not r["errors"] and r["bitwise_restore"], r
    assert r["to_generation"] > r["promoted_generation"], r
    assert r["pod_generations"] == [r["to_generation"]], r

    tl = _deploy_timeline(obs_dir)
    for kind in ("deploy_candidate", "deploy_shadow_start",
                 "deploy_shadow_verdict", "deploy_promote",
                 "slo_burn_start", "deploy_rollback"):
        assert kind in tl, (kind, tl)
    assert tl.index("deploy_promote") < tl.index("slo_burn_start"), tl
    assert tl.index("slo_burn_start") < tl.index("deploy_rollback"), tl
    r["timeline"] = tl
    return r


SCENARIOS = {
    "baseline": scenario_baseline,
    "sigkill": scenario_sigkill,
    "sigterm": scenario_sigterm,
    "nan": scenario_nan,
    "truncate": scenario_truncate,
    "data_worker_kill": scenario_data_worker_kill,
    "data_worker_wedge": scenario_data_worker_wedge,
    "cache_corrupt": scenario_cache_corrupt,
    "data_service_dead": scenario_data_service_dead,
    "eval_sigkill": scenario_eval_sigkill,
    "eval_corrupt": scenario_eval_corrupt,
    "overload": scenario_overload,
    "hang": scenario_hang,
    "replica_kill": scenario_replica_kill,
    "replica_wedge": scenario_replica_wedge,
    "tenant_starvation": scenario_tenant_starvation,
    "swap_under_load": scenario_swap_under_load,
    "fleet_drain": scenario_fleet_drain,
    "fleet_scale": scenario_fleet_scale,
    "host_kill": scenario_host_kill,
    "host_partition": scenario_host_partition,
    "cross_host_swap": scenario_cross_host_swap,
    "deploy_reject": scenario_deploy_reject,
    "deploy_rollback": scenario_deploy_rollback,
}

# Scenarios that restore/compare against baseline's checkpoint.
NEEDS_BASELINE = {
    "sigkill", "sigterm", "truncate", "eval_sigkill", "eval_corrupt",
    "data_worker_kill", "data_worker_wedge", "cache_corrupt",
    "data_service_dead",
}

# Scenarios that exercise the threaded serving plane: `--lockcheck auto`
# (the default) runs these with MX_RCNN_LOCKCHECK=1 so every child —
# including the fabric's per-host subprocesses, which inherit the
# environment — gets instrumented locks.  The sanitizer is deliberately
# NOT defaulted on for the training scenarios: their children assert
# bitwise-exact resume, and instrumentation has no business there.
LOCKCHECK_SCENARIOS = {
    "overload", "hang", "replica_kill", "replica_wedge",
    "tenant_starvation", "swap_under_load", "fleet_drain", "fleet_scale",
    "host_kill", "host_partition", "cross_host_swap",
    "deploy_reject", "deploy_rollback",
}


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "--child":
        rest = argv[2:] if argv[1:2] == ["--"] else argv[1:]
        return child_main(rest)
    if argv and argv[0] == "--child-eval":
        rest = argv[2:] if argv[1:2] == ["--"] else argv[1:]
        return child_eval_main(rest)
    if argv and argv[0] == "--child-overload":
        return child_overload_main()
    if argv and argv[0] == "--child-hang":
        return child_hang_main()
    if argv and argv[0] == "--child-replica-kill":
        return child_replica_kill_main()
    if argv and argv[0] == "--child-replica-wedge":
        return child_replica_wedge_main()
    if argv and argv[0] == "--child-tenant-starvation":
        return child_tenant_starvation_main()
    if argv and argv[0] == "--child-swap":
        return child_swap_main()
    if argv and argv[0] == "--child-fleet-drain":
        return child_fleet_drain_main()
    if argv and argv[0] == "--child-fleet-scale":
        return child_fleet_scale_main()
    if argv and argv[0] == "--child-host-kill":
        return child_host_kill_main()
    if argv and argv[0] == "--child-host-partition":
        return child_host_partition_main()
    if argv and argv[0] == "--child-cross-host-swap":
        return child_cross_host_swap_main()
    if argv and argv[0] == "--child-deploy-reject":
        return child_deploy_reject_main()
    if argv and argv[0] == "--child-deploy-rollback":
        return child_deploy_rollback_main()
    if argv and argv[0] == "--compare":
        return compare_main(argv[1], argv[2])

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", default="all",
                   help="'all', one scenario name, or a comma-separated "
                        "list (baseline is prepended automatically when a "
                        "listed scenario needs it)")
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--workdir", default=None,
                   help="scratch root (default: a fresh temp dir)")
    p.add_argument("--keep", action="store_true",
                   help="keep the scratch root for inspection")
    p.add_argument("--timeout", type=float, default=900.0,
                   help="per-child wall clock budget (seconds)")
    p.add_argument("--scenario-timeout", type=float, default=None,
                   help="hard per-scenario budget; on expiry every live "
                        "child is SIGKILLed and the scenario is marked "
                        "failed (default: 1.5 x --timeout)")
    p.add_argument("--lockcheck", choices=("auto", "on", "off"),
                   default="auto",
                   help="run children under the runtime lock-order "
                        "sanitizer (MX_RCNN_LOCKCHECK=1): 'auto' enables "
                        "it for the fleet/fabric scenarios, 'on'/'off' "
                        "force it everywhere/nowhere")
    args = p.parse_args(argv)
    scenario_timeout = args.scenario_timeout or 1.5 * args.timeout

    root = args.workdir or tempfile.mkdtemp(prefix="mx_rcnn_chaos_")
    if args.scenario == "all":
        names = list(SCENARIOS)
    else:
        names = [n.strip() for n in args.scenario.split(",") if n.strip()]
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            p.error(f"unknown scenario(s) {unknown}; "
                    f"known: {', '.join(SCENARIOS)}")
    # Recovery scenarios restore/compare baseline's checkpoint; pure
    # engine scenarios (overload/hang) don't pay for a training run.
    if "baseline" not in names and NEEDS_BASELINE & set(names):
        names.insert(0, "baseline")

    results: dict[str, dict] = {}
    failed = []
    for name in names:
        t0 = time.monotonic()
        # Env (not argv) so it reaches every process a scenario spawns,
        # transitively: _json_child children, Child-managed servers, and
        # the fabric hosts they fork in turn.
        lockcheck_on = args.lockcheck == "on" or (
            args.lockcheck == "auto" and name in LOCKCHECK_SCENARIOS
        )
        if lockcheck_on:
            os.environ["MX_RCNN_LOCKCHECK"] = "1"
        else:
            os.environ.pop("MX_RCNN_LOCKCHECK", None)
        # Hard backstop above the per-child timeout: a scenario whose
        # orchestration half wedges (not just the child) gets its entire
        # process tree reaped rather than hanging the suite.
        timed_out = threading.Event()
        timer = threading.Timer(
            scenario_timeout,
            lambda: (timed_out.set(), reap_orphans()),
        )
        timer.daemon = True
        timer.start()
        try:
            r = SCENARIOS[name](root, args.steps, args.timeout)
            r["ok"] = True
        except (AssertionError, Exception) as e:  # noqa: BLE001 - report all
            err = f"{type(e).__name__}: {e}"
            if timed_out.is_set():
                err = (f"scenario timed out after {scenario_timeout:.0f}s "
                       f"(children reaped); {err}")
            r = {"ok": False, "error": err}
            failed.append(name)
        finally:
            timer.cancel()
            leaked = reap_orphans()
            if leaked:
                print(f"[chaos] {name}: reaped {leaked} leftover "
                      f"subprocess(es)", file=sys.stderr)
        r["seconds"] = round(time.monotonic() - t0, 1)
        results[name] = r
        print(f"[chaos] {name}: {r}", file=sys.stderr)
        if name == "baseline" and not r["ok"]:
            break  # nothing to compare against
    os.environ.pop("MX_RCNN_LOCKCHECK", None)
    print(json.dumps({"root": root, "steps": args.steps, "results": results}))
    if not args.keep and not failed:
        shutil.rmtree(root, ignore_errors=True)
    elif failed:
        print(f"[chaos] artifacts kept at {root}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
