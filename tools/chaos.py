"""Chaos harness: fault-inject a real training subprocess, prove recovery.

The robustness claims in docs/robustness.md are cheap to assert and easy
to regress silently — so this harness drives the REAL CLI (`train_cli`)
as a subprocess on hermetic CPU (tiny_synthetic preset) and injects the
faults the runtime is supposed to survive:

  baseline  uninterrupted run; its final checkpoint is the bitwise oracle
            for every recovery scenario below.
  sigkill   SIGKILL (no grace, mid-flight) once a mid-run checkpoint
            lands; resume with --resume; final params must be
            BIT-IDENTICAL to baseline's.
  sigterm   SIGTERM mid-run; the child must drain the in-flight step,
            write the emergency checkpoint and exit RESUMABLE_EXIT_CODE;
            resume; bit-identical final params.
  nan       arm the loader's NaN hook (MX_RCNN_CHAOS_NAN_STEPS) for one
            batch; the guardian must roll back, skip the window and
            finish with every logged metric finite.
  truncate  SIGKILL mid-run, then truncate the newest checkpoint's files
            (simulating a kill inside the write); the resumed child must
            fall back to the previous step and STILL converge to
            baseline's exact params.

Bit-identity holds because recovery re-runs the same compiled program
over the same data schedule from the same restored state — it is the
strongest possible "nothing was lost, nothing was double-applied" check
and it needs no tolerance tuning.

Usage:
  python tools/chaos.py [--scenario all|baseline|sigkill|sigterm|nan|truncate]
                        [--steps 12] [--workdir DIR] [--keep] [--timeout 900]

Prints one JSON summary line on stdout; exits non-zero if any scenario
fails.  (`--child` / `--compare` are internal subprocess entry modes.)
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = "tiny_synthetic"
CKPT_EVERY = 3
LOG_EVERY = 2


def _hermetic_cpu() -> None:
    """CPU-only jax in THIS interpreter (same guards as tests/conftest.py:
    the image's sitecustomize registers a TPU-tunnel PJRT plugin whose
    retries can block even cpu backend init)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, REPO_ROOT)
    import jax
    from jax._src import xla_bridge as _xb

    assert isinstance(_xb._backend_factories, dict)
    for name in list(_xb._backend_factories):
        if name not in ("cpu", "tpu"):
            _xb._backend_factories.pop(name, None)
    jax.config.update("jax_platforms", "cpu")
    from mx_rcnn_tpu.utils.compile_cache import configure_cpu_cache

    configure_cpu_cache(REPO_ROOT)


# -- internal subprocess modes ------------------------------------------------


def child_main(argv: list[str]) -> int:
    """Run the real train CLI hermetically (the orchestrator's workload)."""
    _hermetic_cpu()
    from mx_rcnn_tpu.cli import train_cli

    return train_cli.cli(argv)


def compare_main(dir_a: str, dir_b: str) -> int:
    """Bitwise-compare the newest checkpoints of two run dirs."""
    _hermetic_cpu()
    import numpy as np

    import jax
    from mx_rcnn_tpu.train.checkpoint import restore_raw

    a, b = restore_raw(dir_a), restore_raw(dir_b)
    fa, ta = jax.tree_util.tree_flatten(a)
    fb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb:
        print(json.dumps({"equal": False, "why": "tree structure differs"}))
        return 1
    diffs = [
        i for i, (x, y) in enumerate(zip(fa, fb))
        if not np.array_equal(np.asarray(x), np.asarray(y))
    ]
    print(json.dumps({"equal": not diffs, "leaves": len(fa), "diffs": diffs}))
    return 1 if diffs else 0


# -- orchestrator -------------------------------------------------------------


def train_argv(workdir: str, steps: int, resume: bool = False) -> list[str]:
    argv = [
        sys.executable, os.path.abspath(__file__), "--child", "--",
        "--config", CONFIG, "--workdir", workdir,
        "--steps", str(steps), "--no-eval",
        "--set", f"train.checkpoint_every={CKPT_EVERY}",
        "--set", f"train.log_every={LOG_EVERY}",
    ]
    if resume:
        argv.append("--resume")
    return argv


def ckpt_dir(workdir: str) -> str:
    return os.path.join(workdir, CONFIG, "ckpt")


def finalized_steps(workdir: str) -> list[int]:
    """Finalized orbax step dirs (bare ints; tmp dirs have suffixes)."""
    d = ckpt_dir(workdir)
    if not os.path.isdir(d):
        return []
    return sorted(
        int(n) for n in os.listdir(d)
        if n.isdigit() and os.path.isdir(os.path.join(d, n))
    )


def metrics_rows(workdir: str) -> list[dict]:
    path = os.path.join(workdir, CONFIG, "metrics.jsonl")
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    return rows


class Child:
    def __init__(self, workdir: str, steps: int, resume: bool = False,
                 env: dict | None = None) -> None:
        self.log_path = os.path.join(
            workdir, f"child-{'resume' if resume else 'first'}.log"
        )
        os.makedirs(workdir, exist_ok=True)
        self._log = open(self.log_path, "a")
        self.proc = subprocess.Popen(
            train_argv(workdir, steps, resume),
            stdout=self._log, stderr=subprocess.STDOUT,
            env={**os.environ, **(env or {})}, cwd=REPO_ROOT,
        )

    def wait(self, timeout: float) -> int:
        try:
            return self.proc.wait(timeout)
        finally:
            self._log.close()

    def signal(self, sig: int) -> None:
        self.proc.send_signal(sig)

    def log_tail(self, n: int = 30) -> str:
        with open(self.log_path) as f:
            return "".join(f.readlines()[-n:])


def wait_for(predicate, timeout: float, poll: float = 0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(poll)
    return None


def run_to_completion(workdir: str, steps: int, timeout: float,
                      resume: bool = False, env: dict | None = None) -> int:
    child = Child(workdir, steps, resume=resume, env=env)
    rc = child.wait(timeout)
    if rc not in (0,):
        raise AssertionError(
            f"child exited {rc} (log: {child.log_path})\n{child.log_tail()}"
        )
    return rc


def bitwise_equal(workdir_a: str, workdir_b: str, timeout: float) -> bool:
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--compare",
         ckpt_dir(workdir_a), ckpt_dir(workdir_b)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO_ROOT,
    )
    sys.stderr.write(out.stdout + out.stderr)
    return out.returncode == 0


def interrupt_at_checkpoint(workdir: str, steps: int, sig: int,
                            min_step: int, timeout: float) -> int:
    """Start a run, deliver ``sig`` once a checkpoint >= min_step is
    finalized, return the exit code."""
    child = Child(workdir, steps)
    hit = wait_for(
        lambda: [s for s in finalized_steps(workdir) if s >= min_step],
        timeout,
    )
    if not hit:
        child.signal(signal.SIGKILL)
        child.wait(timeout)
        raise AssertionError(
            f"no checkpoint >= {min_step} appeared within {timeout}s "
            f"(log: {child.log_path})\n{child.log_tail()}"
        )
    child.signal(sig)
    return child.wait(timeout)


def scenario_baseline(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "baseline")
    done = finalized_steps(wd)
    if done and done[-1] == steps:  # idempotent across partial reruns
        return {"final_step": steps, "reused": True}
    run_to_completion(wd, steps, timeout)
    final = finalized_steps(wd)
    assert final and final[-1] == steps, f"final checkpoints: {final}"
    return {"final_step": final[-1]}


def scenario_sigkill(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "sigkill")
    rc = interrupt_at_checkpoint(
        wd, steps, signal.SIGKILL, min_step=CKPT_EVERY, timeout=timeout
    )
    assert rc == -signal.SIGKILL, f"expected SIGKILL death, got rc={rc}"
    interrupted_at = finalized_steps(wd)[-1]
    assert interrupted_at < steps, "child finished before the kill landed"
    run_to_completion(wd, steps, timeout, resume=True)
    assert finalized_steps(wd)[-1] == steps
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "resumed-after-SIGKILL params differ from the uninterrupted run"
    )
    return {"killed_after_step": interrupted_at, "bit_identical": True}


def scenario_sigterm(root: str, steps: int, timeout: float) -> dict:
    # Pinned contract (EX_TEMPFAIL) — mirrored from train/preemption.py so
    # the orchestrator stays import-free; test_robustness pins the value.
    RESUMABLE_EXIT_CODE = 75

    wd = os.path.join(root, "sigterm")
    rc = interrupt_at_checkpoint(
        wd, steps, signal.SIGTERM, min_step=CKPT_EVERY, timeout=timeout
    )
    assert rc == RESUMABLE_EXIT_CODE, (
        f"expected resumable exit {RESUMABLE_EXIT_CODE}, got {rc}"
    )
    emergency = finalized_steps(wd)[-1]
    assert emergency < steps
    run_to_completion(wd, steps, timeout, resume=True)
    assert finalized_steps(wd)[-1] == steps
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "resumed-after-SIGTERM params differ from the uninterrupted run"
    )
    return {"emergency_step": emergency, "bit_identical": True}


def scenario_nan(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "nan")
    poison = CKPT_EVERY + 2  # inside the second checkpoint interval
    run_to_completion(
        wd, steps, timeout, env={"MX_RCNN_CHAOS_NAN_STEPS": str(poison)}
    )
    assert finalized_steps(wd)[-1] == steps
    rows = metrics_rows(wd)
    assert rows and rows[-1]["step"] == steps, f"metrics rows: {rows}"
    bad = [
        (r["step"], k) for r in rows for k, v in r.items()
        if isinstance(v, float) and v != v  # NaN
    ]
    assert not bad, f"non-finite metrics survived the rollback: {bad}"
    return {"poisoned_batch": poison, "metric_rows": len(rows)}


def scenario_truncate(root: str, steps: int, timeout: float) -> dict:
    wd = os.path.join(root, "truncate")
    rc = interrupt_at_checkpoint(
        wd, steps, signal.SIGKILL, min_step=2 * CKPT_EVERY, timeout=timeout
    )
    assert rc == -signal.SIGKILL
    latest = finalized_steps(wd)[-1]
    # Truncate every file of the newest checkpoint — a kill mid-write.
    clipped = 0
    for dirpath, _, files in os.walk(os.path.join(ckpt_dir(wd), str(latest))):
        for name in files:
            path = os.path.join(dirpath, name)
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(size // 2)
            clipped += 1
    assert clipped, f"checkpoint step {latest} has no files to truncate"
    run_to_completion(wd, steps, timeout, resume=True)
    assert finalized_steps(wd)[-1] == steps
    assert bitwise_equal(os.path.join(root, "baseline"), wd, timeout), (
        "recovery past the truncated checkpoint lost bit-identity"
    )
    return {"truncated_step": latest, "files_clipped": clipped,
            "bit_identical": True}


SCENARIOS = {
    "baseline": scenario_baseline,
    "sigkill": scenario_sigkill,
    "sigterm": scenario_sigterm,
    "nan": scenario_nan,
    "truncate": scenario_truncate,
}


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "--child":
        rest = argv[2:] if argv[1:2] == ["--"] else argv[1:]
        return child_main(rest)
    if argv and argv[0] == "--compare":
        return compare_main(argv[1], argv[2])

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", default="all",
                   choices=["all", *SCENARIOS])
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--workdir", default=None,
                   help="scratch root (default: a fresh temp dir)")
    p.add_argument("--keep", action="store_true",
                   help="keep the scratch root for inspection")
    p.add_argument("--timeout", type=float, default=900.0,
                   help="per-child wall clock budget (seconds)")
    args = p.parse_args(argv)

    root = args.workdir or tempfile.mkdtemp(prefix="mx_rcnn_chaos_")
    names = list(SCENARIOS) if args.scenario == "all" else [args.scenario]
    # Every recovery scenario compares against baseline's checkpoint.
    if "baseline" not in names:
        names.insert(0, "baseline")

    results: dict[str, dict] = {}
    failed = []
    for name in names:
        t0 = time.monotonic()
        try:
            r = SCENARIOS[name](root, args.steps, args.timeout)
            r["ok"] = True
        except (AssertionError, Exception) as e:  # noqa: BLE001 - report all
            r = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            failed.append(name)
        r["seconds"] = round(time.monotonic() - t0, 1)
        results[name] = r
        print(f"[chaos] {name}: {r}", file=sys.stderr)
        if name == "baseline" and not r["ok"]:
            break  # nothing to compare against
    print(json.dumps({"root": root, "steps": args.steps, "results": results}))
    if not args.keep and not failed:
        shutil.rmtree(root, ignore_errors=True)
    elif failed:
        print(f"[chaos] artifacts kept at {root}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
