"""Continuous-deployment watcher: stage, gate, and roll new checkpoints.

Runs a serving fleet, arms a live SLO engine, and starts a
:class:`mx_rcnn_tpu.ctrl.Deployer` over ``--ckpt-dir``: every validated
checkpoint step that lands while the watcher runs is shadow-staged on a
spare out-of-rotation replica, gated on bitwise parity / golden-set mAP
/ a shadow-scoped SLO against mirrored live traffic, promoted through
the zero-downtime roll, and watched for a post-promote burn (automatic
rollback under a new, higher generation).  Knobs come from
``cfg.ctrl.deploy`` (see docs/deployment.md); CLI flags override.

Synthetic open-loop traffic (``--qps``) keeps the mirror fed when no
external callers exist.  One JSON line on stdout summarizes every
decision; the full timeline replays from ``--obs-dir`` via
``tools/obs_report.py``.

Usage:
    python tools/deploy_watch.py --ckpt-dir /ckpts --duration 60 \\
        --fake-engines --obs-dir /tmp/deploy_obs
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.loadgen import _hermetic_cpu  # noqa: E402


def _build_fleet(args):
    if args.fake_engines:
        from tools.soak import _SoakRunner

        from mx_rcnn_tpu.serve import FleetRouter, InferenceEngine

        def factory(rid: int) -> InferenceEngine:
            return InferenceEngine(
                _SoakRunner(args.service_time),
                replica_id=rid,
                hang_timeout=60.0,
                max_queue=args.max_queue,
            )

        return FleetRouter(factory, args.replicas, supervisor_poll=0.1)

    import jax

    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.detection import TwoStageDetector, init_detector
    from mx_rcnn_tpu.serve import build_fleet

    cfg = get_config(args.config)
    variables = init_detector(
        TwoStageDetector(cfg=cfg.model), jax.random.PRNGKey(0),
        cfg.data.image_size,
    )
    return build_fleet(
        cfg, variables, args.replicas,
        engine_kwargs={"hang_timeout": 300.0, "max_queue": args.max_queue},
        supervisor_poll=0.1,
    )


def run_watch(args: argparse.Namespace) -> dict:
    import numpy as np

    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.ctrl import SLOEngine, build_deployer, default_slos
    from mx_rcnn_tpu.serve import ServeError

    obs.configure(args.obs_dir)
    print(f"[deploy_watch] obs: run_id={obs.run_id()} dir={obs.out_dir()}",
          file=sys.stderr)

    cfg = get_config(args.config)
    fleet = _build_fleet(args)
    fleet.start()
    print(f"[deploy_watch] fleet of {args.replicas} ready; watching "
          f"{args.ckpt_dir}", file=sys.stderr)

    dc = cfg.ctrl.deploy
    live_slo = SLOEngine(
        default_slos(cfg.ctrl),
        fast_s=dc.burn_fast_s, slow_s=dc.burn_slow_s,
        burn_factor=dc.burn_factor,
    ).start(args.ctrl_period)

    overrides = {
        k: v for k, v in (
            ("mirror_rate", args.mirror_rate),
            ("min_mirrored", args.min_mirrored),
            ("shadow_window_s", args.shadow_window),
            ("watch_window_s", args.watch_window),
            ("poll_s", args.poll),
        ) if v is not None
    }
    dep = build_deployer(
        cfg, fleet, ckpt_dir=args.ckpt_dir, live_slo=live_slo, **overrides
    ).start(recover=True)

    img = np.zeros((48, 48, 3), np.float32)
    completed = failed = 0
    lock = threading.Lock()
    deadline = time.monotonic() + args.duration
    stop = threading.Event()

    def pump() -> None:
        nonlocal completed, failed
        while not stop.is_set() and time.monotonic() < deadline:
            try:
                fleet.infer(img, timeout=60.0)
                with lock:
                    completed += 1
            except ServeError:
                with lock:
                    failed += 1
            time.sleep(1.0 / max(args.qps, 0.1))

    pumps = [
        threading.Thread(target=pump, name=f"deploy-watch-pump-{i}",
                         daemon=True)
        for i in range(args.pump_threads)
    ]
    for t in pumps:
        t.start()
    try:
        while time.monotonic() < deadline:
            time.sleep(0.25)
    finally:
        stop.set()
        for t in pumps:
            t.join(60)
        dep.stop()
        live_slo.stop()
        fleet.stop()
        obs.close()

    decisions = [
        {k: v for k, v in h.items() if k != "slo_verdicts"}
        for h in dep.history
    ]
    return {
        "ckpt_dir": os.path.abspath(args.ckpt_dir),
        "obs_dir": os.path.abspath(args.obs_dir),
        "decisions": decisions,
        "promotions": sum(
            1 for h in dep.history if h["kind"] == "deploy_promote"
        ),
        "rollbacks": sum(
            1 for h in dep.history if h["kind"] == "deploy_rollback"
        ),
        "rejections": sum(
            1 for h in dep.history if h["kind"] == "deploy_reject"
        ),
        "generation": fleet.generation,
        "completed": completed,
        "failed": failed,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ckpt-dir", required=True,
                   help="checkpoint dir to watch for validated steps")
    p.add_argument("--duration", type=float, default=30.0)
    p.add_argument("--qps", type=float, default=40.0,
                   help="synthetic open-loop traffic per pump thread")
    p.add_argument("--pump-threads", type=int, default=2)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--ctrl-period", type=float, default=0.5)
    p.add_argument("--config", default="tiny_synthetic")
    p.add_argument("--fake-engines", action="store_true",
                   help="runner-protocol fakes instead of real models")
    p.add_argument("--service-time", type=float, default=0.005,
                   help="--fake-engines: per-request service time")
    p.add_argument("--mirror-rate", type=float, default=None,
                   help="override cfg.ctrl.deploy.mirror_rate")
    p.add_argument("--min-mirrored", type=int, default=None)
    p.add_argument("--shadow-window", type=float, default=None)
    p.add_argument("--watch-window", type=float, default=None)
    p.add_argument("--poll", type=float, default=None,
                   help="override cfg.ctrl.deploy.poll_s")
    p.add_argument("--obs-dir", default=None,
                   help="obs journal dir (default: a temp dir)")
    args = p.parse_args(argv)
    if args.obs_dir is None:
        import tempfile

        args.obs_dir = tempfile.mkdtemp(prefix="deploy_watch_obs_")
    _hermetic_cpu(args.replicas + 1)  # +1: the spare shadow replica

    rec = run_watch(args)
    print(json.dumps(rec))
    print(f"[deploy_watch] {rec['promotions']} promoted, "
          f"{rec['rejections']} rejected, {rec['rollbacks']} rolled "
          f"back; fleet at generation {rec['generation']}",
          file=sys.stderr)
    return 0 if rec["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
