"""fleetlint CLI: concurrency + contract lint for the serving plane.

Runs the static layer of mx_rcnn_tpu/analysis/fleetlint.py — the
lock-order/threading rules (FL001–FL005) over ``serve/ obs/ ctrl/ data/
tools/`` plus the repo-level contract rules (FL010 typed-error
vocabulary + RPC status-map totality, FL011 journal-kind/metric
registry, FL012 cfg-knob docs) — diffs the findings against the
committed baseline (``fleetlint_baseline.json``) and writes
``artifacts/fleetlint_report.json``.  Only NEW findings fail.

The runtime twin (the lock-order sanitizer) is
mx_rcnn_tpu/analysis/lockcheck.py, activated with MX_RCNN_LOCKCHECK=1 —
``tools/chaos.py --lockcheck`` threads it into every fleet scenario.

Usage:
  python tools/fleetlint.py --check              # CI gate: exit 1 on any
                                                 # new finding
  python tools/fleetlint.py                      # report only, exit 0
  python tools/fleetlint.py --no-contracts [paths...]  # concurrency
                                                 # rules only
  python tools/fleetlint.py --write-baseline     # refreeze (review the
                                                 # diff!)

Pure AST — no jax import, no accelerator, sub-second.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on new findings")
    ap.add_argument("--no-contracts", action="store_true",
                    help="skip FL010-FL012 (concurrency rules only)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current findings as the baseline")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO_ROOT,
                                         "fleetlint_baseline.json"))
    ap.add_argument("--out",
                    default=os.path.join(REPO_ROOT, "artifacts",
                                         "fleetlint_report.json"))
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files for the concurrency rules "
                         "(default: all fleet modules)")
    args = ap.parse_args(argv)

    # Pure-AST import path: mx_rcnn_tpu.analysis.fleetlint does not pull
    # in jax, so the linter stays fast even on a machine with no
    # accelerator stack at all.
    from mx_rcnn_tpu.analysis.baseline import (
        collect_counts,
        load_baseline,
        new_findings,
        write_baseline,
    )
    from mx_rcnn_tpu.analysis.fleetlint import (
        RULES,
        fleet_files,
        lint_paths,
    )

    findings = lint_paths(
        REPO_ROOT, args.paths or None,
        contracts=not args.no_contracts,
    )
    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(f"baseline frozen: {len(findings)} findings -> "
              f"{args.baseline}", file=sys.stderr)
    baseline = load_baseline(args.baseline)
    new = new_findings(findings, baseline)

    report = {
        "rules": RULES,
        "static": {
            "files_scanned": len(args.paths or fleet_files(REPO_ROOT)),
            "total_findings": len(findings),
            "baselined": len(findings) - len(new),
            "new": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "snippet": f.snippet, "fingerprint": f.fingerprint()}
                for f in new
            ],
            "per_rule": {
                rule: sum(1 for f in findings if f.rule == rule)
                for rule in sorted(RULES)
            },
            "fingerprints": collect_counts(findings),
        },
        "ok": not new,
    }
    for f in new:
        print(f"NEW {f.format()}", file=sys.stderr)
    if new:
        print(f"fleetlint: {len(new)} new finding(s) beyond baseline",
              file=sys.stderr)
    else:
        print(f"fleetlint: clean ({len(findings)} baselined finding(s))",
              file=sys.stderr)

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}", file=sys.stderr)
    print(json.dumps({"metric": "fleetlint_ok", "value": bool(report["ok"])}))
    if args.check and new:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
