"""Open-loop load generator for the serving fleet (BENCH_serving).

Drives a real :class:`~mx_rcnn_tpu.serve.fleet.FleetRouter` (tiny model,
random params, hermetic CPU with one fake device per replica) for a
fixed duration and reports the latency distribution over *completed*
requests plus the fleet's own counters.  Open-loop means arrivals are
scheduled on the wall clock, not gated on responses — a slow fleet falls
behind and the backlog shows up as shed requests and a fat tail, exactly
like production.

The arrival rate follows a ``--profile`` (shared with tools/soak.py via
:func:`make_profile`):

* ``constant`` — ``--qps`` throughout (the default; unchanged behavior).
* ``sine`` — a compressed diurnal curve: ``qps * (1 + amplitude *
  sin(2*pi*t/period))``, so the fleet sees a trough and a peak every
  ``--period`` seconds.
* ``spike`` — ``--qps`` baseline with a burst of ``qps *
  spike-factor`` for the first ``--duty`` fraction of every
  ``--period`` seconds: the autoscaler-rehearsal shape.

Optionally (``--kill-one``) a replica is killed at the midpoint, which
exercises quarantine -> rebuild -> reinstatement *under load*: the bench
passes only if accepted requests keep completing and p99 stays under the
``--assert-p99`` bound while a replica is out.

``--clients N`` switches to a CLOSED-loop shape instead: N concurrent
small clients each submit one request, wait for its response, and
immediately submit the next — the many-small-callers traffic that
cross-request packing (``--batch-size > 1``) exists for.  The
BENCH_serving line always reports batch occupancy (mean + p50 over
device calls) and ``sustained_qps_per_replica``; ``--assert-occupancy``
gates on the mean.

``--tenants 'victim:weight=4,qps=5;flood:rate=6,burst=4,qps=30,role=flooder'``
runs one open-loop schedule PER TENANT: each entry names a tenant, its
offered ``qps`` (plus an optional per-tenant ``profile``), its policy
knobs (``weight/rate/burst/priority`` — forwarded into
``serve.tenancy.table`` when driving a local fleet), and an optional
``role=flooder`` marker.  Every request carries its tenant token;
``QuotaExceeded`` rejections are counted per tenant as ``quota``
(distinct from ``shed``), and the BENCH line gains a per-tenant table.
``--assert-tenant-isolation FACTOR`` runs a flooder-free baseline phase
first and exits nonzero unless every non-flooder tenant's p99 in the
full mix stays within FACTOR of its solo baseline (noisy-neighbor
isolation, docs/serving.md).

``--targets hostA:port,hostB:port`` swaps the local fleet for an
in-process :class:`~mx_rcnn_tpu.serve.gateway.GatewayRouter` over REAL
host processes (tools/serve_host.py), and ``--gateway URL`` drives a
remote fabric endpoint over RPC — same schedule, same BENCH line, with
``hosts`` listing every host that served traffic (``["local"]`` for the
single-process default).

The driven router carries a content-addressed result cache by default
(``--result-cache N`` capacity, 0 disables; see serve/result_cache.py):
duplicate images are answered without a device call and identical
in-flight requests coalesce onto one.  ``--dup-frac F`` makes that
fraction of arrivals re-send one hot image to rehearse duplicate-heavy
traffic; the BENCH line reports ``cache_hits`` and ``coalesced``.

Prints diagnostics to stderr and exactly one ``BENCH_serving`` JSON line
as the LAST line on stdout:

    {"bench": "serving", "replicas": 2, "hosts": ["local"],
     "qps": 6.0, "duration_s": 15.0,
     "submitted": 90, "completed": 88, "shed": 2, "failed": 0,
     "p50_s": 0.21, "p99_s": 0.57, "max_s": 0.61,
     "killed_rid": 0, "quarantines": 1, "reinstatements": 1,
     "hedges": 0, "retries": 1, "generation": 0}

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=2 \\
    JAX_PLATFORMS=cpu python tools/loadgen.py \\
        --replicas 2 --qps 6 --duration 15 --kill-one --assert-p99 60
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
from typing import Callable

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROFILES = ("constant", "sine", "spike")


def make_profile(
    name: str,
    qps: float,
    *,
    amplitude: float = 0.5,
    period_s: float = 60.0,
    spike_factor: float = 4.0,
    duty: float = 0.15,
) -> Callable[[float], float]:
    """Arrival-rate schedule ``rate(t_elapsed) -> req/s``.

    Shared by the loadgen CLI and the soak harness so both rehearse the
    same traffic shapes.  Rates are floored at a small positive value —
    an open loop with rate exactly 0 would never schedule the next
    arrival and the clock math below divides by it.
    """
    if qps <= 0:
        raise ValueError("qps must be > 0")
    if name == "constant":
        return lambda t: qps
    if name == "sine":
        if not 0.0 <= amplitude <= 1.0:
            raise ValueError("amplitude must be in [0, 1]")
        return lambda t: max(
            0.05, qps * (1.0 + amplitude * math.sin(2 * math.pi * t / period_s))
        )
    if name == "spike":
        if not 0.0 < duty < 1.0:
            raise ValueError("duty must be in (0, 1)")
        return lambda t: (
            qps * spike_factor if (t % period_s) < duty * period_s else qps
        )
    raise ValueError(f"unknown profile {name!r} (want one of {PROFILES})")


_TENANT_POLICY_KEYS = ("weight", "rate", "burst", "priority")


def parse_tenant_load_spec(spec: str) -> list[dict]:
    """``--tenants`` entries: ``name:k=v,...;name2:...`` where the keys
    are the ``serve.tenancy`` policy knobs plus the load-side ``qps``,
    ``profile`` and ``role`` (``role=flooder`` marks the adversary the
    isolation gate excludes from its baseline).  Shared with
    tools/soak.py so both rehearse the same tenant mixes.
    """
    out: list[dict] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, kvs = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"tenant entry missing a name: {part!r}")
        ent = {"name": name, "qps": None, "profile": "constant",
               "role": "normal", "policy": {}}
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            key, sep, val = kv.partition("=")
            key, val = key.strip(), val.strip()
            if not sep:
                raise ValueError(f"tenant {name!r}: malformed knob {kv!r}")
            if key == "qps":
                ent["qps"] = float(val)
            elif key == "profile":
                if val not in PROFILES:
                    raise ValueError(
                        f"tenant {name!r}: unknown profile {val!r}"
                    )
                ent["profile"] = val
            elif key == "role":
                ent["role"] = val
            elif key in _TENANT_POLICY_KEYS:
                ent["policy"][key] = val
            else:
                raise ValueError(
                    f"tenant {name!r}: unknown knob {key!r} (expected "
                    f"qps/profile/role or one of {_TENANT_POLICY_KEYS})"
                )
        out.append(ent)
    if not out:
        raise ValueError("empty --tenants spec")
    if len({e["name"] for e in out}) != len(out):
        raise ValueError("duplicate tenant name in --tenants spec")
    return out


def tenant_table_string(specs: list[dict]) -> str:
    """Rebuild the ``serve.tenancy.table`` string from parsed entries
    (policy knobs only — qps/profile/role are load-side)."""
    return ";".join(
        e["name"] + ":" + ",".join(
            f"{k}={v}" for k, v in e["policy"].items()
        )
        for e in specs
    )


def _hermetic_cpu(n_devices: int) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class _RemoteFuture:
    """FleetRequest-shaped handle over one remote RPC inference."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._result = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("remote request not complete")
        if self._error is not None:
            raise self._error
        return self._result


class _RemoteGateway:
    """FleetRouter-shaped driver for a REMOTE fabric endpoint
    (``--gateway URL``): submit/stats/stop over serve/rpc.py's client,
    each submit running its blocking RPC on a daemon thread."""

    def __init__(self, url: str) -> None:
        from mx_rcnn_tpu.serve import RpcClient

        self.client = RpcClient(url)

    def submit(self, image, timeout=None, trace_id=None,
               tenant=None) -> _RemoteFuture:
        fut = _RemoteFuture()

        def run() -> None:
            try:
                fut._result = self.client.infer(
                    image, deadline_s=timeout, trace_id=trace_id,
                    tenant=tenant,
                )
            except BaseException as e:  # noqa: BLE001 - carried to result()
                fut._error = e
            finally:
                fut._event.set()

        threading.Thread(target=run, daemon=True).start()
        return fut

    def stats(self) -> dict:
        return self.client.stats()["fleet"]

    def stop(self, timeout=None) -> None:
        del timeout


def _build_driver(args, cfg):
    """(fleet-shaped driver, hosts list) for the three serving surfaces:
    a local FleetRouter (default), an in-process GatewayRouter over
    ``--targets``, or a remote fabric endpoint via ``--gateway URL``."""
    if args.gateway:
        drv = _RemoteGateway(args.gateway)
        stats = drv.stats()  # fail fast when the endpoint is down
        hosts = sorted(stats.get("hosts", {})) or [args.gateway]
        print(f"[loadgen] driving remote gateway {args.gateway} "
              f"(hosts: {', '.join(hosts)})", file=sys.stderr)
        return drv, hosts
    if args.targets:
        from mx_rcnn_tpu.serve import GatewayRouter, ResultCache

        targets = [t.strip() for t in args.targets.split(",") if t.strip()]
        gw = GatewayRouter(
            targets, hedge_after=None, probe_interval_s=0.25,
            result_cache=(
                ResultCache(capacity=args.result_cache)
                if args.result_cache > 0 else None
            ),
        ).start()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if gw.stats()["replicas"] >= len(targets):
                break
            time.sleep(0.1)
        stats = gw.stats()
        if stats["replicas"] == 0:
            raise RuntimeError(f"no routable host among {targets}")
        hosts = sorted(stats["hosts"])
        print(f"[loadgen] gateway over {len(hosts)} host(s): "
              f"{', '.join(hosts)} ({stats['replicas']} routable)",
              file=sys.stderr)
        return gw, hosts
    return None, ["local"]


def run_bench(args: argparse.Namespace) -> dict:
    import numpy as np

    import jax
    from mx_rcnn_tpu.config import apply_overrides, get_config
    from mx_rcnn_tpu.detection import TwoStageDetector, init_detector
    from mx_rcnn_tpu.serve import (
        Overloaded, QuotaExceeded, ServeError, build_fleet,
    )

    from mx_rcnn_tpu import obs

    obs_on = bool(args.obs_dir)
    if obs_on:
        # Durable plane: journal + per-request spans under --obs-dir and
        # (optionally) a live /metrics endpoint to scrape mid-run.
        obs.configure(
            args.obs_dir, metrics_port=args.metrics_port, flush_s=5.0
        )
        print(f"[loadgen] obs: run_id={obs.run_id()} dir={obs.out_dir()} "
              f"metrics_port={obs.metrics_port()}", file=sys.stderr)

    cfg = get_config(args.config)
    tenant_specs = getattr(args, "_tenant_specs", None)
    if tenant_specs:
        # A local fleet enforces the tenant table itself; fabric modes
        # only carry the tokens (the remote hosts own their policy).
        cfg = apply_overrides(cfg, [
            "serve.tenancy.enabled=true",
            f"serve.tenancy.table={tenant_table_string(tenant_specs)}",
        ])
    fleet, hosts = _build_driver(args, cfg)
    if fleet is None:
        variables = init_detector(
            TwoStageDetector(cfg=cfg.model), jax.random.PRNGKey(0),
            cfg.data.image_size,
        )
        from mx_rcnn_tpu.serve import ResultCache

        fleet = build_fleet(
            cfg, variables, args.replicas,
            batch_size=args.batch_size,
            engine_kwargs={
                "hang_timeout": 300.0, "max_queue": args.max_queue,
                "pack": not args.no_pack, "pack_window_s": args.pack_window,
            },
            supervisor_poll=0.1,
            hedge_after="auto",
            result_cache=(
                ResultCache(capacity=args.result_cache)
                if args.result_cache > 0 else None
            ),
        )
        print(f"[loadgen] starting {args.replicas} replica(s) "
              f"(warmup compiles)...", file=sys.stderr)
        fleet.start()
        print("[loadgen] fleet ready", file=sys.stderr)
    args._hosts = hosts
    if obs_on:
        obs.register_status("fleet", fleet.stats)

    rng = np.random.default_rng(0)
    h, w = cfg.data.image_size
    images = [rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
              for _ in range(4)]

    def pick_image(i: int, base: int):
        # --dup-frac: that fraction of arrivals re-send one hot image
        # (duplicate-heavy traffic: retry storms, hot thumbnails), evenly
        # interleaved so dups overlap in flight; the rest cycle the
        # distinct pool as before.
        f = args.dup_frac
        if f > 0.0 and math.floor((i + 1) * f) > math.floor(i * f):
            return images[0]
        return images[base % len(images)]

    lock = threading.Lock()
    latencies: list[float] = []
    submitted = shed = quota = failed = 0
    pending: list = []
    tstats: dict[str, dict] = {
        e["name"]: {"submitted": 0, "shed": 0, "quota": 0, "failed": 0,
                    "lat": []}
        for e in (tenant_specs or [])
    }

    def collect(freq, t_submit: float, tenant: str | None = None) -> None:
        nonlocal shed, quota, failed
        ts = tstats.get(tenant)
        try:
            freq.result(timeout=args.deadline + 60.0)
        except QuotaExceeded:
            # The tenant's own budget, not fleet pressure — kept apart
            # from shed on both the global and per-tenant rows.
            with lock:
                quota += 1
                if ts is not None:
                    ts["quota"] += 1
            return
        except Overloaded:
            # Fabric modes surface admission-control shedding at result
            # time (the remote 429 comes back on the response path).
            with lock:
                shed += 1
                if ts is not None:
                    ts["shed"] += 1
            return
        except ServeError:
            with lock:
                failed += 1
                if ts is not None:
                    ts["failed"] += 1
            return
        lat = time.monotonic() - t_submit
        with lock:
            latencies.append(lat)
            if ts is not None:
                ts["lat"].append(lat)

    killed_rid = None
    if args.clients > 0:
        # Closed loop: N concurrent small clients, each waiting for its
        # response before submitting again — per-caller concurrency is 1,
        # so only CROSS-request packing can fill a micro-batch.
        t0 = time.monotonic()
        deadline_wall = t0 + args.duration
        kill_lock = threading.Lock()

        def client(ci: int) -> None:
            nonlocal submitted, shed, failed, killed_rid
            sent = 0
            while True:
                now = time.monotonic()
                if now >= deadline_wall:
                    return
                if args.kill_one and now - t0 >= args.duration / 2.0:
                    with kill_lock:
                        if killed_rid is None:
                            killed_rid = 0
                            fleet.kill_replica(0, "loadgen --kill-one")
                            print(f"[loadgen] killed replica 0 at "
                                  f"t={now - t0:.1f}s", file=sys.stderr)
                trace_id = obs.new_trace_id() if obs_on else None
                sent += 1
                try:
                    freq = fleet.submit(
                        pick_image(sent, ci),
                        timeout=args.deadline, trace_id=trace_id,
                    )
                except Overloaded:
                    with lock:
                        submitted += 1
                        shed += 1
                    time.sleep(0.01)
                    continue
                except ServeError as e:
                    with lock:
                        submitted += 1
                        failed += 1
                    print(f"[loadgen] submit failed: {e}", file=sys.stderr)
                    time.sleep(0.05)
                    continue
                with lock:
                    submitted += 1
                try:
                    freq.result(timeout=args.deadline + 60.0)
                except Overloaded:
                    with lock:
                        shed += 1
                    continue
                except ServeError:
                    with lock:
                        failed += 1
                    continue
                with lock:
                    latencies.append(time.monotonic() - now)

        clients = [
            threading.Thread(target=client, args=(ci,), daemon=True)
            for ci in range(args.clients)
        ]
        for t in clients:
            t.start()
        for t in clients:
            t.join(timeout=args.duration + args.deadline + 120.0)
        return _finish(args, fleet, latencies, submitted, shed, failed,
                       killed_rid, obs_on)
    if tenant_specs:
        # One open-loop schedule per tenant: each tenant's arrivals are
        # clocked independently at its own qps/profile, so the flooder
        # falling behind (or bouncing off its quota) never slows the
        # victims' offered load.
        t0 = time.monotonic()
        deadline_wall = t0 + args.duration
        n_tenants = len(tenant_specs)

        def tenant_loop(ent: dict) -> None:
            nonlocal submitted, shed, quota, failed
            name = ent["name"]
            ts = tstats[name]
            rate = make_profile(
                ent["profile"],
                ent["qps"] if ent["qps"] else max(args.qps / n_tenants, 0.1),
                amplitude=args.amplitude, period_s=args.period,
                spike_factor=args.spike_factor, duty=args.duty,
            )
            next_at = t0
            sent = 0
            while True:
                now = time.monotonic()
                if now >= deadline_wall:
                    return
                if now < next_at:
                    time.sleep(min(next_at - now, 0.05))
                    continue
                next_at += 1.0 / rate(now - t0)
                trace_id = obs.new_trace_id() if obs_on else None
                sent += 1
                try:
                    freq = fleet.submit(
                        pick_image(sent, sent), timeout=args.deadline,
                        trace_id=trace_id, tenant=name,
                    )
                except QuotaExceeded:
                    with lock:
                        submitted += 1
                        quota += 1
                        ts["submitted"] += 1
                        ts["quota"] += 1
                    continue
                except Overloaded:
                    with lock:
                        submitted += 1
                        shed += 1
                        ts["submitted"] += 1
                        ts["shed"] += 1
                    continue
                except ServeError as e:
                    with lock:
                        submitted += 1
                        failed += 1
                        ts["submitted"] += 1
                        ts["failed"] += 1
                    print(f"[loadgen] {name}: submit failed: {e}",
                          file=sys.stderr)
                    continue
                with lock:
                    submitted += 1
                    ts["submitted"] += 1
                t = threading.Thread(
                    target=collect, args=(freq, now, name), daemon=True
                )
                t.start()
                pending.append(t)

        loops = [
            threading.Thread(target=tenant_loop, args=(e,), daemon=True)
            for e in tenant_specs
        ]
        for t in loops:
            t.start()
        for t in loops:
            t.join(timeout=args.duration + 120.0)
        for t in list(pending):
            t.join(timeout=args.deadline + 120.0)
        return _finish(args, fleet, latencies, submitted, shed, failed,
                       killed_rid, obs_on, quota=quota, tstats=tstats,
                       tenant_specs=tenant_specs)
    rate = make_profile(
        args.profile, args.qps,
        amplitude=args.amplitude, period_s=args.period,
        spike_factor=args.spike_factor, duty=args.duty,
    )
    t0 = time.monotonic()
    next_at = t0
    deadline_wall = t0 + args.duration
    while True:
        now = time.monotonic()
        if now >= deadline_wall:
            break
        if now < next_at:
            time.sleep(min(next_at - now, 0.05))
            continue
        # Open loop: the schedule advances whether or not this arrival
        # is admitted, so a slow fleet accumulates lateness (and sheds)
        # instead of quietly throttling the offered load.  The interval
        # is re-derived from the profile each arrival, so sine/spike
        # shapes modulate inter-arrival gaps, not batch sizes.
        next_at += 1.0 / rate(now - t0)
        if args.kill_one and killed_rid is None and \
                now - t0 >= args.duration / 2.0:
            killed_rid = 0
            fleet.kill_replica(0, "loadgen --kill-one")
            print(f"[loadgen] killed replica 0 at "
                  f"t={now - t0:.1f}s", file=sys.stderr)
        # Every synthetic request carries its own trace id; with --obs-dir
        # the whole span tree (request -> attempt -> engine queue/device)
        # lands in <obs-dir>/spans.jsonl keyed by it.
        trace_id = obs.new_trace_id() if obs_on else None
        try:
            freq = fleet.submit(pick_image(submitted, submitted),
                                timeout=args.deadline, trace_id=trace_id)
        except QuotaExceeded:
            with lock:
                submitted += 1
                quota += 1
            continue
        except Overloaded:
            with lock:
                submitted += 1
                shed += 1
            continue
        except ServeError as e:
            with lock:
                submitted += 1
                failed += 1
            print(f"[loadgen] submit failed: {e}", file=sys.stderr)
            continue
        with lock:
            submitted += 1
        t = threading.Thread(target=collect, args=(freq, now), daemon=True)
        t.start()
        pending.append(t)

    for t in pending:
        t.join(timeout=args.deadline + 120.0)
    return _finish(args, fleet, latencies, submitted, shed, failed,
                   killed_rid, obs_on, quota=quota)


def _occupancy_summary() -> dict:
    """Aggregate the ``serve_batch_occupancy`` histogram across every
    replica/level series: device-call count, mean fill, p50 fill."""
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.obs import metrics as metrics_mod

    snap = obs.histogram(
        "serve_batch_occupancy",
        "request slots filled / slots total per device call",
    ).snapshot()
    series = [s for s in snap.values() if s.get("count")]
    calls = sum(s["count"] for s in series)
    if not calls:
        return {"device_calls": 0, "mean": None, "p50": None}
    le = series[0]["le"]
    merged = [0] * len(le)
    for s in series:
        for i, c in enumerate(s["buckets"]):
            merged[i] += c
    return {
        "device_calls": calls,
        "mean": round(sum(s["sum"] for s in series) / calls, 4),
        "p50": round(
            metrics_mod.percentile_from_counts(le, merged, 0.50), 4
        ),
    }


def _finish(args, fleet, latencies, submitted, shed, failed, killed_rid,
            obs_on, quota=0, tstats=None, tenant_specs=None) -> dict:
    from mx_rcnn_tpu import obs

    stats = fleet.stats()
    # Generous stop budget: --kill-one leaves a background rebuild whose
    # warmup compile cannot be interrupted; stop() waits it out.
    fleet.stop(timeout=240.0)

    latencies.sort()
    host_detail = stats.get("hosts")
    hosts = (
        sorted(host_detail) if isinstance(host_detail, dict) and host_detail
        else list(getattr(args, "_hosts", ["local"]))
    )
    rec = {
        "bench": "serving",
        "replicas": args.replicas,
        "hosts": hosts,
        "qps": args.qps,
        "profile": args.profile,
        "clients": args.clients,
        "batch_size": args.batch_size,
        "pack": not args.no_pack,
        "duration_s": args.duration,
        "submitted": submitted,
        "completed": len(latencies),
        "shed": shed,
        "quota": quota,
        "failed": failed,
        "sustained_qps_per_replica": round(
            len(latencies) / args.duration / max(args.replicas, 1), 3
        ),
        "p50_s": round(_percentile(latencies, 0.50), 4),
        "p99_s": round(_percentile(latencies, 0.99), 4),
        "max_s": round(max(latencies), 4) if latencies else float("nan"),
        "occupancy": _occupancy_summary(),
        "cache_hits": (stats.get("cache") or {}).get("hits", 0),
        "coalesced": (stats.get("cache") or {}).get("coalesced", 0),
        "killed_rid": killed_rid,
        "quarantines": stats["quarantines"],
        "reinstatements": stats["reinstatements"],
        "hedges": stats["hedges"],
        "retries": stats["retries"],
        "generation": stats["generation"],
    }
    if tstats is not None and tenant_specs is not None:
        roles = {e["name"]: e["role"] for e in tenant_specs}
        tenants = {}
        for name, ts in tstats.items():
            lat = sorted(ts["lat"])
            tenants[name] = {
                "role": roles.get(name, "normal"),
                "submitted": ts["submitted"],
                "completed": len(lat),
                "shed": ts["shed"],
                "quota": ts["quota"],
                "failed": ts["failed"],
                "p50_s": round(_percentile(lat, 0.50), 4),
                "p99_s": round(_percentile(lat, 0.99), 4),
            }
        rec["tenants"] = tenants
        if isinstance(stats.get("tenancy"), dict):
            rec["tenancy"] = stats["tenancy"]
    if obs_on:
        port = obs.metrics_port()
        if port is not None:
            # Self-scrape: prove the endpoint serves non-empty metrics
            # for the run we just generated.
            import urllib.request

            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            n_series = sum(
                1 for ln in body.splitlines()
                if ln and not ln.startswith("#")
            )
            print(f"[loadgen] /metrics scrape: {n_series} series",
                  file=sys.stderr)
            rec["metrics_series"] = n_series
        rec["obs"] = {
            "run_id": obs.run_id(),
            "dir": obs.out_dir(),
            "journal": os.path.join(obs.out_dir(), "journal.jsonl"),
            "spans": os.path.join(obs.out_dir(), "spans.jsonl"),
        }
        obs.close()
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--qps", type=float, default=6.0,
                   help="open-loop arrival rate (requests/second); the "
                        "baseline rate for non-constant profiles")
    p.add_argument("--profile", choices=PROFILES, default="constant",
                   help="traffic shape over the window (see module doc)")
    p.add_argument("--amplitude", type=float, default=0.5,
                   help="sine profile: fractional swing around --qps")
    p.add_argument("--period", type=float, default=60.0,
                   help="sine/spike profile: cycle length in seconds")
    p.add_argument("--spike-factor", type=float, default=4.0,
                   help="spike profile: burst rate as a multiple of --qps")
    p.add_argument("--duty", type=float, default=0.15,
                   help="spike profile: fraction of each period spent "
                        "bursting")
    p.add_argument("--duration", type=float, default=15.0,
                   help="load window in seconds")
    p.add_argument("--deadline", type=float, default=120.0,
                   help="per-request deadline in seconds")
    p.add_argument("--max-queue", type=int, default=64,
                   help="per-replica admission queue bound")
    p.add_argument("--clients", type=int, default=0,
                   help="closed-loop mode: this many concurrent "
                        "one-request-at-a-time clients instead of the "
                        "open-loop --qps schedule (0 = open loop)")
    p.add_argument("--batch-size", type=int, default=None,
                   help="per-replica micro-batch slots (device call "
                        "width); default follows cfg.serve.batch_size")
    p.add_argument("--no-pack", action="store_true",
                   help="disable continuous batching (one caller's "
                        "same-plan run per device call, as before)")
    p.add_argument("--pack-window", type=float, default=0.0,
                   help="seconds the worker lingers for stragglers to "
                        "top off a partial batch")
    p.add_argument("--config", default="tiny_synthetic")
    p.add_argument("--targets", default="",
                   help="drive an IN-PROCESS gateway over these "
                        "comma-separated host addrs (tools/serve_host.py "
                        "fleets) instead of a local fleet")
    p.add_argument("--gateway", default="",
                   help="drive a REMOTE fabric endpoint (gateway or "
                        "single host) at this base URL / addr")
    p.add_argument("--kill-one", action="store_true",
                   help="kill replica 0 at the midpoint of the window")
    p.add_argument("--result-cache", type=int, default=256,
                   help="content-addressed result cache capacity on the "
                        "driven router (0 disables; see docs/serving.md)")
    p.add_argument("--dup-frac", type=float, default=0.0,
                   help="fraction of arrivals that re-send one hot image "
                        "(duplicate-heavy traffic for the result cache)")
    p.add_argument("--tenants", default="",
                   help="per-tenant open-loop mix: 'name:qps=5,weight=4;"
                        "flood:qps=30,rate=6,role=flooder' — policy "
                        "knobs feed serve.tenancy.table on a local "
                        "fleet; see docs/serving.md")
    p.add_argument("--assert-tenant-isolation", type=float, default=None,
                   help="with --tenants: run a flooder-free baseline "
                        "first and exit nonzero unless every non-flooder "
                        "tenant's p99 in the full mix is within this "
                        "factor of its solo baseline")
    p.add_argument("--assert-p50", type=float, default=None,
                   help="exit nonzero unless p50 latency (s) is under "
                        "this bound")
    p.add_argument("--assert-p99", type=float, default=None,
                   help="exit nonzero unless p99 latency (s) is under "
                        "this bound and no accepted request failed")
    p.add_argument("--assert-occupancy", type=float, default=None,
                   help="exit nonzero unless mean batch occupancy "
                        "(slots filled / slots total per device call) "
                        "is at least this bound")
    p.add_argument("--obs-dir", default=None,
                   help="write the obs journal, per-request span files "
                        "and flight dumps under this directory")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="with --obs-dir: bind /metrics here (0 = "
                        "ephemeral, shown on stderr)")
    args = p.parse_args(argv)
    if args.targets and args.gateway:
        p.error("--targets and --gateway are mutually exclusive")
    if args.kill_one and (args.targets or args.gateway):
        p.error("--kill-one drives a LOCAL fleet; use tools/chaos.py "
                "host_kill for fabric-level failure injection")
    tenant_specs = None
    if args.tenants:
        if args.clients > 0 or args.kill_one:
            p.error("--tenants is an open-loop multi-tenant mix; it "
                    "composes with neither --clients nor --kill-one")
        try:
            tenant_specs = parse_tenant_load_spec(args.tenants)
        except ValueError as e:
            p.error(str(e))
        args._tenant_specs = tenant_specs
    if args.assert_tenant_isolation is not None:
        if not tenant_specs:
            p.error("--assert-tenant-isolation requires --tenants")
        if all(e["role"] != "flooder" for e in tenant_specs):
            p.error("--assert-tenant-isolation needs a role=flooder "
                    "tenant to remove in the baseline phase")
    _hermetic_cpu(args.replicas)

    baseline = None
    if args.assert_tenant_isolation is not None:
        # Phase A: the same victims at the same rates, flooder removed
        # (and no obs plane — one journal per process).  Its record goes
        # to stderr only; the BENCH contract stays one-stdout-line.
        import copy

        base_args = copy.copy(args)
        base_args._tenant_specs = [
            e for e in tenant_specs if e["role"] != "flooder"
        ]
        base_args.obs_dir = None
        print("[loadgen] isolation baseline: flooder-free phase...",
              file=sys.stderr)
        baseline = run_bench(base_args)
        print(f"[loadgen] baseline record: {json.dumps(baseline)}",
              file=sys.stderr)

    rec = run_bench(args)
    if baseline is not None:
        rec["isolation"] = {
            "factor": args.assert_tenant_isolation,
            "baseline_p99_s": {
                name: t["p99_s"]
                for name, t in baseline["tenants"].items()
            },
        }
    print(json.dumps(rec))

    ok = True
    if rec["completed"] == 0:
        print("[loadgen] FAIL: no request completed", file=sys.stderr)
        ok = False
    if rec["failed"] != 0:
        print(f"[loadgen] FAIL: {rec['failed']} accepted request(s) "
              f"failed", file=sys.stderr)
        ok = False
    if args.kill_one and rec["quarantines"] < 1:
        print("[loadgen] FAIL: --kill-one but no quarantine observed",
              file=sys.stderr)
        ok = False
    if args.assert_p50 is not None and not rec["p50_s"] < args.assert_p50:
        print(f"[loadgen] FAIL: p50 {rec['p50_s']}s >= bound "
              f"{args.assert_p50}s", file=sys.stderr)
        ok = False
    if args.assert_p99 is not None and not rec["p99_s"] < args.assert_p99:
        print(f"[loadgen] FAIL: p99 {rec['p99_s']}s >= bound "
              f"{args.assert_p99}s", file=sys.stderr)
        ok = False
    if args.assert_occupancy is not None:
        mean_occ = rec["occupancy"]["mean"]
        if mean_occ is None or mean_occ < args.assert_occupancy:
            print(f"[loadgen] FAIL: mean batch occupancy {mean_occ} < "
                  f"bound {args.assert_occupancy}", file=sys.stderr)
            ok = False
    if args.assert_tenant_isolation is not None:
        factor = args.assert_tenant_isolation
        for name, t in rec["tenants"].items():
            if t["role"] == "flooder":
                continue
            if t["completed"] == 0:
                print(f"[loadgen] FAIL: tenant {name} completed nothing "
                      f"in the mix phase", file=sys.stderr)
                ok = False
                continue
            solo = rec["isolation"]["baseline_p99_s"].get(name)
            mix = t["p99_s"]
            # The 50 ms floor keeps sub-tick solo baselines from turning
            # scheduler noise into a flaky gate.
            if solo is None or not mix <= factor * max(solo, 0.05):
                print(f"[loadgen] FAIL: tenant {name} p99 {mix}s vs "
                      f"flooder-free baseline {solo}s exceeds factor "
                      f"{factor}", file=sys.stderr)
                ok = False
        if ok:
            print(f"[loadgen] tenant isolation HELD (factor {factor})",
                  file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
