"""Per-component MFU/FLOP attribution report for the train step.

Walks the traced train program (utils/hlo_profile.py) and writes a JSON
report attributing every MXU FLOP to a model component — stem, C2..C5,
FPN, RPN-head, ROI, box-head — so "20.6% MFU" decomposes into per-region
shares instead of one opaque number.  The attribution is an abstract
trace: it runs under ``JAX_PLATFORMS=cpu`` for the full TPU-shaped recipe
program (no execution, no device).  Timing and the post-fusion HLO
instruction summary are optional extras for hosts that can afford to
execute/compile the program.

Usage:
  python tools/mfu_report.py [--config r50_fpn_coco] [--set K=V ...]
      [--out artifacts/mfu_report.json]
      [--compare-legacy]   also attribute the pre-PR dense layout
                           (stem_s2d/stem_pool_fold/c2_pad/packed_head off)
                           so the report shows WHERE the restructured
                           components moved the FLOP mix
      [--hlo]              compile and add per-component instruction counts
      [--time N]           execute N timed steps and add measured ms/step,
                           achieved TFLOP/s and MFU vs the v5e bf16 peak
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LEGACY_LAYOUT_OVERRIDES = (
    "model.backbone.stem_s2d=false",
    "model.backbone.stem_pool_fold=false",
    "model.backbone.c2_pad=false",
    "model.rpn.packed_head=false",
)


def _variant(cfg, args, label: str) -> dict:
    import jax

    from bench import V5E_PEAK_BF16_FLOPS, _synthetic_batch
    from mx_rcnn_tpu.train.loop import build_all
    from mx_rcnn_tpu.utils.hlo_profile import (
        component_report,
        hlo_component_summary,
    )

    k = max(cfg.train.steps_per_call, 1)
    batch = cfg.train.per_device_batch
    image_size = cfg.data.image_size
    print(
        f"[{label}] tracing {args.config} @ {image_size[0]}x{image_size[1]} "
        f"b{batch} k{k} ...",
        file=sys.stderr,
    )
    model, tx, state, step_fn, global_batch = build_all(cfg, mesh=None)
    data = _synthetic_batch(cfg, batch, image_size, k)

    dt_per_step = None
    if args.time:
        data = jax.device_put(data)
        state, metrics = step_fn(state, data)  # compile + warm
        leaf = jax.tree_util.tree_leaves(state.params)[0]
        jax.device_get((metrics["loss"], leaf.ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(args.time):
            state, metrics = step_fn(state, data)
        leaf = jax.tree_util.tree_leaves(state.params)[0]
        jax.device_get((metrics["loss"], leaf.ravel()[0]))
        dt_per_step = (time.perf_counter() - t0) / (args.time * k)

    report = component_report(
        step_fn,
        state,
        data,
        steps_per_call=k,
        dt_per_step=dt_per_step,
        peak_flops=V5E_PEAK_BF16_FLOPS,
    )
    report["layout"] = {
        "stem_s2d": cfg.model.backbone.stem_s2d,
        "stem_pool_fold": cfg.model.backbone.stem_pool_fold,
        "c2_pad": cfg.model.backbone.c2_pad,
        "rpn_packed_head": cfg.model.rpn.packed_head,
    }
    if args.hlo:
        print(f"[{label}] compiling for the HLO summary ...", file=sys.stderr)
        txt = step_fn.lower(state, data).compile().as_text()
        report["hlo_instructions"] = hlo_component_summary(txt)
    return report


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="r50_fpn_coco")
    ap.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="KEY.PATH=VALUE",
    )
    ap.add_argument("--out", default=os.path.join("artifacts", "mfu_report.json"))
    ap.add_argument("--compare-legacy", action="store_true")
    ap.add_argument("--hlo", action="store_true")
    ap.add_argument("--time", type=int, default=0, metavar="N")
    args = ap.parse_args(argv)

    import jax

    from mx_rcnn_tpu.config import apply_overrides, get_config

    cfg = get_config(args.config)
    # Attribution-only runs never execute the program, so the full recipe
    # canvas is free even on CPU; k=1 keeps the jaxpr small (the K-step
    # scan scales every component linearly).
    cfg = dataclasses.replace(
        cfg,
        data=dataclasses.replace(cfg.data, max_gt_boxes=32),
        train=dataclasses.replace(
            cfg.train, steps_per_call=1, per_device_batch=2
        ),
    )
    if args.overrides:
        cfg = apply_overrides(cfg, args.overrides)

    report = {
        "config": args.config,
        "overrides": list(args.overrides),
        "platform": jax.default_backend(),
        "image_size": list(cfg.data.image_size),
        "per_device_batch": cfg.train.per_device_batch,
        "attribution": "analytic conv+dot jaxpr walk per name-stack component"
        " (mx_rcnn_tpu.utils.hlo_profile); timing "
        + ("measured" if args.time else "not measured on this host"),
        "default_layout": _variant(cfg, args, "default"),
    }
    if args.compare_legacy:
        legacy = apply_overrides(cfg, list(LEGACY_LAYOUT_OVERRIDES))
        report["legacy_layout"] = _variant(legacy, args, "legacy")

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}", file=sys.stderr)
    print(json.dumps({
        "metric": "mfu_report_total_tflops_per_step",
        "value": report["default_layout"]["total_tflops_per_step"],
    }))
    return report


if __name__ == "__main__":
    main()
