"""Merge one run's observability artifacts into a single report.

Inputs (all optional — the report carries whatever exists):

* ``<obs-dir>/journal.jsonl``   — typed event journal (mx_rcnn_tpu.obs)
* ``<obs-dir>/spans.jsonl``     — finished spans, one Chrome-trace event
  per line
* ``<obs-dir>/flight_*.json``   — flight-recorder postmortem dumps
* ``--stage-log`` file(s)       — bench/chaos stdout with ``{"metric":
  ...}`` JSON lines (train_stage_ms breakdowns, BENCH headlines)

Outputs:

* ``artifacts/obs_report.json`` (``--out``) — counts per event kind, the
  reconstructed **incident timeline** (kill -> detect -> quarantine/reap
  -> rebuild/respawn -> recover, in journal order), an **slo** section
  (error-budget timeline from the ``slo_error_budget_remaining`` gauge
  in ``metrics_flush`` snapshots, burn alerts, and the autoscaler's
  resize decisions with their input signals — why the fleet changed
  size, from the journal alone), flight-dump summaries and any
  stage/headline lines.
* ``<obs-dir>/trace.json`` (``--trace-out``) — the span lines wrapped in
  a Chrome-trace ``{"traceEvents": [...]}`` array, loadable in Perfetto
  next to the jax.profiler dumps.

Usage:
    python tools/obs_report.py --obs-dir /tmp/run/obs \\
        --stage-log /tmp/run/bench.log --out artifacts/obs_report.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.obs.metrics import parse_labels  # noqa: E402

# Event kinds that mark state changes in an incident, in no particular
# order — the TIMELINE order comes from the journal, these only filter
# routine chatter (metrics_flush, shed) out of it.
INCIDENT_KINDS = frozenset({
    "worker_death", "worker_retired", "worker_wedged", "service_fallback",
    "cache_quarantine", "shm_quarantine", "cache_evict",
    "guardian_rollback", "rollback_restored", "guardian_loss_spike",
    "training_diverged", "preempt_drain",
    "checkpoint_saved", "checkpoint_restored",
    "engine_dead", "engine_killed",
    "fleet_quarantine", "fleet_reinstate", "fleet_retire", "weight_swap",
    "breaker_transition", "ladder_transition",
    "slo_burn_start", "slo_burn_stop",
    "fleet_scale_up", "fleet_scale_down",
    "fleet_replica_added", "fleet_replica_retired",
    "gateway_weight_roll",
    "deploy_candidate", "deploy_shadow_start", "deploy_shadow_verdict",
    "deploy_promote", "deploy_reject", "deploy_rollback", "deploy_resume",
    "tenant_quota_tightened", "tenant_quota_restored",
})


def _slo_section(journal: list[dict], t0: float) -> dict:
    """Control-plane story from the journal alone: the error-budget
    trajectory (every ``metrics_flush`` snapshot carries the
    ``slo_error_budget_remaining{slo=...}`` gauge), burn-alert
    transitions, and the fleet-resize decisions with the signals the
    autoscaler acted on."""
    budget_timeline: list[dict] = []
    burn_alerts: list[dict] = []
    resize_decisions: list[dict] = []
    for rec in journal:
        kind = rec.get("kind")
        payload = rec.get("payload") or {}
        t_s = round(rec.get("ts", t0) - t0, 3)
        if kind == "metrics_flush":
            series = (payload.get("snapshot") or {}).get(
                "slo_error_budget_remaining"
            )
            if isinstance(series, dict) and series:
                point = {"t_s": t_s}
                for label, v in series.items():
                    # label is 'slo="availability"' — keep just the value.
                    name = label.split('"')[1] if '"' in label else label
                    point[name] = round(v, 6) if isinstance(v, float) else v
                budget_timeline.append(point)
        elif kind in ("slo_burn_start", "slo_burn_stop"):
            burn_alerts.append({
                "t_s": t_s, "event": kind.rsplit("_", 1)[-1],
                **{k: v for k, v in payload.items()},
            })
        elif kind in ("fleet_scale_up", "fleet_scale_down"):
            resize_decisions.append({
                "t_s": t_s,
                "direction": kind.rsplit("_", 1)[-1],
                **{k: v for k, v in payload.items()},
            })
    return {
        "budget_timeline": budget_timeline,
        "burn_alerts": burn_alerts,
        "resize_decisions": resize_decisions,
    }


def _pack_section(journal: list[dict]) -> dict:
    """Packing / zero-copy efficiency from the last ``metrics_flush``
    snapshot: the ``serve_batch_occupancy`` histogram collapsed to a
    device-call count + mean fill, and the shm ring counters — so a
    report always answers "were the device calls full and did the data
    plane copy" without re-scraping /metrics."""
    snap: dict = {}
    for rec in journal:
        if rec.get("kind") == "metrics_flush":
            s = (rec.get("payload") or {}).get("snapshot") or {}
            if s:
                snap = s  # keep the LAST flush (cumulative series)
    out: dict = {}
    occ = snap.get("serve_batch_occupancy")
    if isinstance(occ, dict) and occ:
        calls = sum(
            v.get("count", 0) for v in occ.values() if isinstance(v, dict)
        )
        filled = sum(
            v.get("sum", 0.0) for v in occ.values() if isinstance(v, dict)
        )
        out["batch_occupancy"] = {
            "device_calls": calls,
            "mean": round(filled / calls, 4) if calls else None,
        }
    for name in ("data_shm_bytes_total", "data_shm_ring_stalls_total",
                 "data_shm_quarantines_total",
                 "serve_cache_hits_total", "serve_cache_coalesced_total",
                 "serve_cache_evictions_total"):
        series = snap.get(name)
        if isinstance(series, dict) and series:
            out[name] = round(sum(
                v for v in series.values() if isinstance(v, (int, float))
            ), 2)
    size = snap.get("serve_cache_size")
    if isinstance(size, dict) and size:
        # Gauge: last value wins per series; one shared cache per router.
        vals = [v for v in size.values() if isinstance(v, (int, float))]
        if vals:
            out["serve_cache_size"] = vals[-1]
    return out


def _tenant_section(journal: list[dict], t0: float) -> dict:
    """Per-tenant story when multi-tenancy ran (docs/serving.md): request
    outcomes from the ``tenant``-labelled ``fleet_requests_total`` rows of
    the last ``metrics_flush`` snapshot, quota rejections from
    ``serve_quota_exceeded_total``, and the per-tenant burn/governor
    timeline (burn transitions on tenant-scoped SLOs plus quota
    tighten/restore actions).  Empty when the run had no tenancy — the
    metrics carry no ``tenant`` label then, by design."""
    snap: dict = {}
    for rec in journal:
        if rec.get("kind") == "metrics_flush":
            s = (rec.get("payload") or {}).get("snapshot") or {}
            if s:
                snap = s  # cumulative series: the LAST flush wins
    tenants: dict[str, dict] = {}

    def ent(name: str) -> dict:
        return tenants.setdefault(name, {
            "requests": {}, "quota_rejections": 0, "timeline": [],
        })

    series = snap.get("fleet_requests_total")
    if isinstance(series, dict):
        for key, v in series.items():
            lbl = parse_labels(key)
            name = lbl.get("tenant")
            if not name or not isinstance(v, (int, float)):
                continue
            outcomes = ent(name)["requests"]
            outcome = lbl.get("outcome", "?")
            outcomes[outcome] = outcomes.get(outcome, 0) + int(round(v))
    series = snap.get("serve_quota_exceeded_total")
    if isinstance(series, dict):
        for key, v in series.items():
            name = parse_labels(key).get("tenant")
            if name and isinstance(v, (int, float)):
                ent(name)["quota_rejections"] += int(round(v))
    for rec in journal:
        kind = rec.get("kind")
        if kind not in ("slo_burn_start", "slo_burn_stop",
                        "tenant_quota_tightened", "tenant_quota_restored"):
            continue
        payload = rec.get("payload") or {}
        name = payload.get("tenant")
        if not name:
            continue  # fleet-wide burn: not one tenant's story
        ent(name)["timeline"].append({
            "t_s": round(rec.get("ts", t0) - t0, 3), "kind": kind,
            **{k: v for k, v in payload.items() if k != "tenant"},
        })
    return tenants


def _read_jsonl(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path, "rb") as f:
        for line in f:
            try:
                rec = json.loads(line.decode("utf-8", "replace"))
            except ValueError:
                continue  # torn/corrupt line — same tolerance as obs.journal
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _order_key(rec: dict):
    # Wall clock first (cross-process), monotonic as the tiebreaker
    # (same-process events can share a rounded wall timestamp).
    return (rec.get("ts", 0.0), rec.get("ts_mono_ns", 0))


def build_report(
    obs_dir: str, stage_logs: tuple[str, ...] = ()
) -> tuple[dict, list[dict]]:
    """(report dict, chrome-trace span events) for one obs directory."""
    journal = sorted(
        _read_jsonl(os.path.join(obs_dir, "journal.jsonl")), key=_order_key
    )
    spans = _read_jsonl(os.path.join(obs_dir, "spans.jsonl"))
    flights = []
    for path in sorted(glob.glob(os.path.join(obs_dir, "flight_*.json"))):
        try:
            with open(path) as f:
                dump = json.load(f)
        except (OSError, ValueError):
            continue
        flights.append({
            "path": path,
            "trigger": dump.get("trigger"),
            "run_id": dump.get("run_id"),
            "entries": len(dump.get("entries", [])),
            "kinds": sorted({
                e.get("kind") for e in dump.get("entries", [])
                if isinstance(e, dict) and e.get("kind")
            }),
        })

    t0 = journal[0]["ts"] if journal else 0.0
    events_by_kind: dict[str, int] = {}
    timeline = []
    for rec in journal:
        kind = rec.get("kind", "?")
        events_by_kind[kind] = events_by_kind.get(kind, 0) + 1
        if kind in INCIDENT_KINDS:
            timeline.append({
                "t_s": round(rec.get("ts", t0) - t0, 3),
                "subsystem": rec.get("subsystem"),
                "kind": kind,
                "pid": rec.get("pid"),
                "payload": rec.get("payload", {}),
            })

    stage_lines = []
    for log_path in stage_logs:
        if not os.path.exists(log_path):
            continue
        with open(log_path, "rb") as f:
            for line in f:
                try:
                    rec = json.loads(line.decode("utf-8", "replace"))
                except ValueError:
                    continue
                if isinstance(rec, dict) and ("metric" in rec or "bench" in rec):
                    stage_lines.append(rec)

    traces: dict[str, int] = {}
    for s in spans:
        tid = (s.get("args") or {}).get("trace_id")
        if tid:
            traces[tid] = traces.get(tid, 0) + 1

    report = {
        "obs_dir": os.path.abspath(obs_dir),
        "run_ids": sorted({r.get("run_id", "-") for r in journal}),
        "journal_records": len(journal),
        "events_by_kind": dict(sorted(events_by_kind.items())),
        "incident_timeline": timeline,
        "slo": _slo_section(journal, t0),
        "tenants": _tenant_section(journal, t0),
        "data_plane": _pack_section(journal),
        "spans": {
            "count": len(spans),
            "traces": len(traces),
            "max_spans_per_trace": max(traces.values(), default=0),
        },
        "flight_dumps": flights,
        "stage_lines": stage_lines,
    }
    return report, spans


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--obs-dir", required=True,
                   help="directory obs.configure() wrote into")
    p.add_argument("--stage-log", action="append", default=[],
                   help="bench/chaos log with JSON metric lines "
                        "(repeatable)")
    p.add_argument("--out", default="artifacts/obs_report.json")
    p.add_argument("--trace-out", default=None,
                   help="Chrome-trace wrap of spans.jsonl (default: "
                        "<obs-dir>/trace.json; 'none' to skip)")
    args = p.parse_args(argv)

    report, spans = build_report(args.obs_dir, tuple(args.stage_log))

    trace_out = args.trace_out
    if trace_out is None:
        trace_out = os.path.join(args.obs_dir, "trace.json")
    if trace_out != "none" and spans:
        with open(trace_out, "w") as f:
            json.dump({"traceEvents": spans}, f)
        report["trace_file"] = os.path.abspath(trace_out)

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"[obs_report] {report['journal_records']} journal record(s), "
          f"{report['spans']['count']} span(s), "
          f"{len(report['flight_dumps'])} flight dump(s) -> {args.out}",
          file=sys.stderr)
    print(json.dumps({
        "metric": "obs_report",
        "value": {
            "events": report["journal_records"],
            "incidents": len(report["incident_timeline"]),
            "spans": report["spans"]["count"],
            "flight_dumps": len(report["flight_dumps"]),
        },
        "path": os.path.abspath(args.out),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
