"""Ablation timing of the train step's sub-graphs on the real chip.

Times successively larger prefixes of the full train computation
(backbone -> +RPN head -> +anchor assignment/RPN losses -> +proposals ->
+sampling+ROIAlign -> full step) so hotspots can be localized without a
device profiler (the axon tunnel exposes no trace).  Every timing is N
queued executions ended by ONE device->host fetch — see BASELINE.md's
timing-method warning: block_until_ready returns at dispatch under the
tunnel; the fetch of the last result waits on the whole queue.

Usage: python tools/perf_breakdown.py [--hw 1024] [--steps 20]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timed(fn, arg, n):
    """Time n dependency-chained executions of ``fn`` (a grad of params).

    Each iteration perturbs the argument with 0 * a leaf of the previous
    output, so execution i+1 provably depends on execution i and the single
    final fetch waits for the whole chain (BASELINE.md timing rule — queue
    order alone is not a trusted synchronization under the axon tunnel).
    """
    out = fn(arg)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])  # compile+sync

    eps = jax.jit(
        lambda a, o: jax.tree_util.tree_map(lambda x, g: x + 0.0 * g, a, o)
    )
    carry = arg
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(carry)
        carry = eps(carry, out)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / n


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hw", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--config", default="r50_fpn_coco")
    ap.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="KEY.PATH=VALUE",
    )
    args = ap.parse_args()

    from mx_rcnn_tpu.config import apply_overrides, get_config
    from mx_rcnn_tpu.detection import Batch, TwoStageDetector, forward_train
    from mx_rcnn_tpu.detection.graph import (
        _pool_rois,
        _propose_one,
        _rpn_losses,
        _slice_levels,
        assign_anchors_cfg,
        init_detector,
        level_anchors,
    )
    from mx_rcnn_tpu.ops import sample_rois

    hw = args.hw
    cfg = get_config(args.config)
    cfg = apply_overrides(
        cfg,
        [f"data.image_size=({hw},{hw})", "data.max_gt_boxes=32"]
        + args.overrides,
    )
    model = TwoStageDetector(cfg=cfg.model)
    variables = init_detector(model, jax.random.PRNGKey(0), (hw, hw))
    params = variables["params"]
    rest = {k: v for k, v in variables.items() if k != "params"}

    rng = np.random.RandomState(0)
    g = cfg.data.max_gt_boxes
    boxes = np.zeros((1, g, 4), np.float32)
    boxes[:, :8] = [100.0, 100.0, 300.0, 300.0]
    batch = Batch(
        images=jnp.asarray(rng.randn(1, hw, hw, 3), jnp.float32),
        image_hw=jnp.full((1, 2), float(hw), jnp.float32),
        gt_boxes=jnp.asarray(boxes),
        gt_classes=jnp.ones((1, g), jnp.int32),
        gt_valid=jnp.asarray(np.arange(g)[None] < 8),
    )
    key = jax.random.PRNGKey(1)
    mcfg = cfg.model

    # Shared front end (mirrors forward_train's structure).  Each stage is
    # "everything before it" + one more piece; all stages keep the RPN loss
    # term so the backbone backward exists in every variant (in the real
    # graph proposals/sampling are stop-grad side computations).
    def front(p, upto: str):
        v = {"params": p, **rest}
        feats = model.apply(v, batch.images, method="features")
        if upto == "backbone":
            return sum(jnp.sum(f.astype(jnp.float32) ** 2) for f in feats.values())
        rpn_out = model.apply(v, feats, method="rpn")
        anchors = level_anchors(mcfg, feats)
        levels = sorted(rpn_out)
        logits = jnp.concatenate([rpn_out[l][0] for l in levels], axis=1)
        deltas = jnp.concatenate([rpn_out[l][1] for l in levels], axis=1)
        if upto == "rpn":
            return sum(
                jnp.sum(o.astype(jnp.float32) ** 2)
                for pair in rpn_out.values() for o in pair
            )
        anchors_cat = jnp.concatenate([anchors[l] for l in levels], axis=0)
        targets = jax.vmap(
            lambda k, gt, gv, hw_: assign_anchors_cfg(
                mcfg, k, anchors_cat, gt, gv, hw_[0], hw_[1]
            )
        )(key[None].repeat(1, 0), batch.gt_boxes, batch.gt_valid, batch.image_hw)
        rpn_cls, rpn_box, _ = _rpn_losses(logits, deltas, targets)
        loss = rpn_cls + rpn_box
        if upto == "rpnloss":
            return loss
        scores = jax.nn.sigmoid(jax.lax.stop_gradient(logits))
        propose = _propose_one(mcfg, train=True)
        props = jax.vmap(
            lambda s, d, hw_: propose(*_slice_levels(levels, anchors, s, d), hw_)
        )(scores, jax.lax.stop_gradient(deltas), batch.image_hw)
        if upto == "proposals":
            return loss + (jnp.sum(props.rois) + jnp.sum(props.scores)) * 1e-30
        samples = jax.vmap(
            lambda k, rois, rv, gt, gc, gv: sample_rois(
                k, rois, rv, gt, gc, gv,
                batch_size=mcfg.rcnn.roi_batch_size,
                fg_fraction=mcfg.rcnn.fg_fraction,
                fg_iou=mcfg.rcnn.fg_iou,
                bg_iou_hi=mcfg.rcnn.bg_iou_hi,
                bg_iou_lo=mcfg.rcnn.bg_iou_lo,
                bbox_weights=mcfg.rcnn.bbox_weights,
            )
        )(key[None].repeat(1, 0), props.rois, props.valid, batch.gt_boxes,
          batch.gt_classes, batch.gt_valid)
        if upto == "sample":
            return loss + jnp.sum(samples.rois) * 1e-30
        pooled = _pool_rois(
            mcfg, feats, samples.rois, mcfg.rcnn.pooled_size, model.roi_levels
        )
        if upto == "pool":
            return loss + jnp.sum(pooled.astype(jnp.float32) ** 2) * 1e-30
        raise ValueError(upto)

    def stage_full(p):
        loss, _ = forward_train(model, {"params": p, **rest}, key, batch)
        return loss

    stages = [
        ("backbone fwd+bwd", lambda p: front(p, "backbone")),
        ("+rpn head", lambda p: front(p, "rpn")),
        ("+assign+rpn losses", lambda p: front(p, "rpnloss")),
        ("+proposal gen (stop-grad)", lambda p: front(p, "proposals")),
        ("+sample_rois (stop-grad)", lambda p: front(p, "sample")),
        ("+roialign (stop-grad)", lambda p: front(p, "pool")),
        ("full forward_train+bwd", stage_full),
    ]
    results = []
    for name, fn in stages:
        grad = jax.jit(jax.grad(fn))
        dt = timed(grad, params, args.steps)
        results.append((name, dt))
        print(f"{name:32s} {dt * 1e3:8.2f} ms/step", flush=True)
    print("\ndeltas vs previous stage:")
    prev = None
    for name, dt in results:
        print(f"{name:32s} +{(dt - (prev if prev is not None else dt)) * 1e3:7.2f} ms")
        prev = dt


if __name__ == "__main__":
    main()
