"""Ablation timing of the train step's sub-graphs on the real chip.

Times successively larger prefixes of the full train computation
(backbone -> +RPN head -> +anchor assignment/RPN losses -> +proposals ->
+sampling+ROIAlign -> full step) so hotspots can be localized without a
device profiler (the axon tunnel exposes no trace).  Every timing is N
queued executions ended by ONE device->host fetch — see BASELINE.md's
timing-method warning: block_until_ready returns at dispatch under the
tunnel; the fetch of the last result waits on the whole queue.

Also times the full optimizer step (make_train_step minus the ablation
grad — optimizer/update overhead) and standalone micro-benches of the
usual non-MXU suspects (per-level proposal NMS fixed point, the big
anchor top_k) so the largest delta line can be attributed inside itself.

Usage: python tools/perf_breakdown.py [--hw 800x1344] [--batch 2] [--steps 20]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# Same persistent compile cache as bench.py: iterating on one stage should
# not recompile the other seven.  Fingerprinted subdir (backend + host
# features): an un-keyed dir on a checkout that migrates between machines
# replays foreign XLA:CPU AOT blobs — SIGILL risk (MULTICHIP_r0* tails).
from mx_rcnn_tpu.utils.compile_cache import configure_cache
from mx_rcnn_tpu.utils.stage_bench import (  # noqa: F401  (timed: re-export)
    time_train_stages,
    timed,
    train_stage_fns,
)

configure_cache(
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    ),
    min_compile_secs=10,
    # This tool migrates between driver hosts with the checkout; when the
    # LLVM-feature probe is unavailable the plain cpuinfo key collided
    # across them (MULTICHIP_r0* SIGILL tails) — separate hosts hard.
    strict_host=True,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--hw", default="800x1344",
        help="canvas as HxW (recipe default) or one square int",
    )
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--config", default="r50_fpn_coco")
    ap.add_argument(
        "--infer", action="store_true",
        help="break down forward_inference (eval path) instead of the "
        "train step: features -> +proposals -> +box head -> full "
        "(per-class NMS + top-D)",
    )
    ap.add_argument(
        "--backbone", action="store_true",
        help="break down the backbone wall one level further: per-stage "
        "trunk fwd+bwd (stem, +C2.., production freeze), the FrozenBN-vs-"
        "identity fusion A/B, the FPN neck delta, and the per-level RPN "
        "head cost",
    )
    ap.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="KEY.PATH=VALUE",
    )
    ap.add_argument(
        "--only", default=None, metavar="SUBSTR",
        help="run only train-breakdown stages whose name contains this "
        "substring (skips the optimizer row and micro-benches too)",
    )
    ap.add_argument(
        "--freeze", action=argparse.BooleanOptionalAction, default=None,
        help="apply the production freeze (stop-grad conv1/bn1/layer1 — "
        "their backward is DCE'd exactly as in the real step).  Default: "
        "follow the config (freeze_stages > 0), matching build_all.  The "
        "r3 tables were recorded with --no-freeze semantics and overstate "
        "the backbone wall by the frozen stages' backward (~20 ms on "
        "R101-FPN at recipe shapes)",
    )
    args = ap.parse_args()

    from mx_rcnn_tpu.config import apply_overrides, get_config
    from mx_rcnn_tpu.detection import Batch, TwoStageDetector
    from mx_rcnn_tpu.detection.graph import init_detector, level_anchors

    if "x" in args.hw:
        h, w = (int(t) for t in args.hw.split("x"))
    else:
        h = w = int(args.hw)
    b = args.batch
    cfg = get_config(args.config)
    cfg = apply_overrides(
        cfg,
        [f"data.image_size=({h},{w})", "data.max_gt_boxes=32"]
        + args.overrides,
    )
    model = TwoStageDetector(cfg=cfg.model)
    variables = init_detector(model, jax.random.PRNGKey(0), (h, w))
    params = variables["params"]
    rest = {k: v for k, v in variables.items() if k != "params"}

    rng = np.random.RandomState(0)
    g = cfg.data.max_gt_boxes
    boxes = np.zeros((b, g, 4), np.float32)
    boxes[:, :8] = [100.0, 100.0, 300.0, 300.0]
    batch = Batch(
        images=jnp.asarray(rng.randn(b, h, w, 3), jnp.float32),
        image_hw=jnp.asarray([[float(h), float(w)]] * b, jnp.float32),
        gt_boxes=jnp.asarray(boxes),
        gt_classes=jnp.ones((b, g), jnp.int32),
        gt_valid=jnp.asarray(np.tile(np.arange(g)[None] < 8, (b, 1))),
    )
    key = jax.random.PRNGKey(1)
    mcfg = cfg.model

    if args.backbone:
        _backbone_breakdown(args, cfg, model, params, rest, batch)
        return
    if args.infer:
        _infer_breakdown(args, model, params, rest, batch, mcfg)
        return

    freeze_on = (
        args.freeze
        if args.freeze is not None
        else cfg.model.backbone.freeze_stages > 0
    )
    if freeze_on:
        from mx_rcnn_tpu.train.loop import FREEZE_PREFIXES
        from mx_rcnn_tpu.train.optim import frozen_mask

        _mask = frozen_mask(
            params, FREEZE_PREFIXES.get(cfg.model.backbone.name, ())
        )

        def masked(p):
            return jax.tree_util.tree_map(
                lambda x, t: x if t else jax.lax.stop_gradient(x), p, _mask
            )
    else:
        def masked(p):
            return p

    # Stage list shared with bench.py --breakdown
    # (mx_rcnn_tpu/utils/stage_bench.py): each stage is "everything before
    # it" + one more piece of forward_train; all keep the RPN loss term so
    # the backbone backward exists in every variant.
    stages = train_stage_fns(model, params, rest, batch, key, masked=masked)
    if args.only:
        stages = [s for s in stages if args.only in s[0]]
    results = time_train_stages(
        stages, params, args.steps,
        report=lambda name, dt: print(
            f"{name:32s} {dt * 1e3:8.2f} ms/step", flush=True
        ),
    )

    if args.only:
        _print_deltas(results, filtered=True)
        return

    # Full production step incl. optimizer (delta vs the grad-only full
    # stage = clip + wd + sgd + state bookkeeping).
    from mx_rcnn_tpu.parallel.step import make_train_step
    from mx_rcnn_tpu.train.loop import FREEZE_PREFIXES
    from mx_rcnn_tpu.train.optim import frozen_mask, make_optimizer
    from mx_rcnn_tpu.train.state import create_train_state

    freeze = FREEZE_PREFIXES.get(cfg.model.backbone.name, ())
    tx, schedule = make_optimizer(cfg.train, params, freeze_prefixes=freeze)
    state = create_train_state(model, tx, jax.random.PRNGKey(0), (h, w), batch=1)
    state = state.replace(params=params, model_state=rest)
    step_fn = make_train_step(
        model, tx, schedule,
        trainable_mask=frozen_mask(params, freeze) if freeze else None,
    )

    # timed() feeds fn its own output; close over state and chain via params.
    def opt_fn(p):
        new_s, _ = step_fn(state.replace(params=p), batch)
        return new_s.params

    dt = timed(jax.jit(opt_fn), params, args.steps)
    results.append(("full step + optimizer", dt))
    print(f"{'full step + optimizer':32s} {dt * 1e3:8.2f} ms/step", flush=True)

    _print_deltas(results)

    # ---- standalone micro-benches of the usual non-MXU suspects ---------
    print("\nisolated micro-benches (forward only, per step):")
    from mx_rcnn_tpu.ops.nms import nms_indices

    feats = model.apply({"params": params, **rest}, batch.images,
                        method="features")
    anchors = level_anchors(mcfg, feats)
    n_anchors = int(sum(a.shape[0] for a in anchors.values()))

    # timed() chains fn's output back into its argument, so each micro fn
    # returns an argument-shaped value that depends on the measured op.
    pre = mcfg.rpn.train_pre_nms_top_n

    # The big per-image objectness top_k over all anchors.
    scores_all = jnp.asarray(rng.rand(b, n_anchors), jnp.float32)
    topk = jax.jit(
        lambda s: s + 0.0 * jax.lax.top_k(s, pre)[0].sum()
    )
    dt = timed(topk, scores_all, args.steps)
    print(f"  top_k({n_anchors} anchors -> {pre}) x{b}   {dt*1e3:8.2f} ms")

    # One per-level NMS fixed point at the proposal count (the train path
    # runs one of these per FPN level per image).
    k = pre
    bx = jnp.asarray(rng.rand(b, k, 4) * 800, jnp.float32)
    bx = bx.at[..., 2:].set(bx[..., :2] + 8 + 120 * rng.rand(b, k, 2))
    post = mcfg.rpn.train_post_nms_top_n
    nms1 = jax.jit(
        lambda s: s + 0.0 * jax.vmap(
            lambda bb, ss: nms_indices(
                bb, ss, mcfg.rpn.nms_threshold, post
            )[0].astype(jnp.float32).sum()
        )(bx, s)[:, None]
    )
    sc = jnp.asarray(rng.rand(b, k), jnp.float32)
    dt = timed(nms1, sc, args.steps)
    n_lvl = len(model.feature_levels)
    print(
        f"  NMS fixed point ({k} boxes) x{b} imgs  {dt*1e3:8.2f} ms"
        f"  (train path runs {n_lvl} levels/img)"
    )


def _print_deltas(results, filtered: bool = False) -> None:
    """``filtered``: a --only run — the first surviving row has no
    predecessor, so its cumulative time is printed as an absolute (a
    '+delta' there would mislabel everything upstream of the filter as
    this stage's cost), and later rows may skip stages in between."""
    print(
        "\ndeltas vs previous stage"
        + (" (filtered: first row is ABSOLUTE; gaps possible):" if filtered else ":")
    )
    prev = None
    for name, dt in results:
        if prev is None and filtered:
            print(f"{name:32s} ={dt * 1e3:8.2f} ms (cumulative)")
        else:
            d = dt - (prev if prev is not None else 0.0)
            print(f"{name:32s} +{d * 1e3:7.2f} ms")
        prev = dt


def _backbone_breakdown(args, cfg, model, params, rest, batch) -> None:
    """One level below the step breakdown (VERDICT r4 #2): WHERE inside
    the backbone wall the time goes.

    - Trunk truncations (stem, +C2, +C3, +C4, +C5): fwd+bwd of a ResNet cut
      after each stage, with the production freeze (conv1/bn1/layer1
      stop-grad — their backward is DCE'd exactly as in the real step).
      Fresh random inits: stage timing is value-independent.
    - FrozenBN fusion A/B: the same full trunk with norm="none" (identity).
      Equal times = the multiply-add fuses into the convs (the claim in
      models/norm.py); a gap = each BN costs an HBM round trip.
    - FPN neck delta: detector.features (trunk+FPN) minus trunk alone, on
      the real variables.
    - RPN head per level: the weight-shared head applied to each pyramid
      level separately (activation bytes halve per level; P2 is the
      prime suspect).
    """
    import jax
    import jax.numpy as jnp
    from flax import traverse_util

    from mx_rcnn_tpu.models.resnet import STAGE_BLOCKS, ResNet

    name = cfg.model.backbone.name
    if name not in STAGE_BLOCKS:
        raise SystemExit(f"--backbone supports ResNets, not {name}")
    blocks = STAGE_BLOCKS[name]
    dtype = jnp.bfloat16
    imgs = batch.images
    key = jax.random.PRNGKey(0)
    b = imgs.shape[0]

    def frozen_stopgrad(p):
        """Production freeze inside a bare trunk tree (FREEZE_PREFIXES
        minus the 'backbone/' scope)."""
        flat = traverse_util.flatten_dict(p)
        out = {
            k: (
                jax.lax.stop_gradient(v)
                if k[0] in ("conv1", "bn1") or k[0].startswith("layer1_")
                else v
            )
            for k, v in flat.items()
        }
        return traverse_util.unflatten_dict(out)

    def time_trunk(m, label):
        vs = m.init(key, imgs)
        p0 = vs["params"]
        r0 = {k: v for k, v in vs.items() if k != "params"}

        def loss(p, im):
            out = m.apply({"params": frozen_stopgrad(p), **r0}, im)
            return sum(jnp.sum(f.astype(jnp.float32) ** 2) for f in out.values())

        def grad_plus(p, im):
            # value_and_grad, with the VALUE folded into the output: the
            # stem+C2 truncation has every param frozen, so its grad is
            # constant zeros and grad alone would let XLA DCE the whole
            # forward — the row would time nothing (0.0 * val survives
            # XLA's IEEE rules like the timing chain's 0.0 * g does).
            val, g = jax.value_and_grad(loss)(p, im)
            return jax.tree_util.tree_map(
                lambda x: x + 0.0 * val.astype(x.dtype), g
            )

        dt = timed(jax.jit(grad_plus), p0, args.steps, extra=imgs)
        from mx_rcnn_tpu.utils.flops import count_matmul_flops

        fl = count_matmul_flops(grad_plus, p0, imgs)
        print(
            f"{label:34s} {dt * 1e3:8.2f} ms/step fwd+bwd"
            f"  ({fl / 1e12:5.2f} TF, {fl / dt / 1e12:5.1f} TF/s)",
            flush=True,
        )
        return dt, fl

    print(f"trunk truncations ({name}, batch {b}, {imgs.shape[1]}x{imgs.shape[2]}):")
    rows = []
    for j, label in ((1, "stem+C2"), (2, "+C3"), (3, "+C4"), (4, "+C5 (full trunk)")):
        m = ResNet(
            blocks=blocks[:j], out_levels=tuple(range(2, j + 2)),
            norm="frozen_bn", dtype=dtype,
        )
        rows.append((label, *time_trunk(m, label)))
    print("\nper-stage deltas (delta-MFU of v5e bf16 peak 197 TF/s):")
    prev_t = prev_f = 0.0
    for label, dt, fl in rows:
        ddt, dfl = dt - prev_t, fl - prev_f
        mfu = dfl / max(ddt, 1e-9) / 197e12 * 100
        print(f"{label:34s} +{ddt * 1e3:7.2f} ms  ({dfl/1e12:5.2f} TF, {mfu:4.1f}% MFU)")
        prev_t, prev_f = dt, fl

    # FrozenBN fusion A/B on the full trunk.
    m_none = ResNet(blocks=blocks, out_levels=(2, 3, 4, 5), norm="none", dtype=dtype)
    dt_none, _ = time_trunk(m_none, "full trunk, norm=none (A/B)")
    dt_bn = rows[-1][1]
    print(
        f"FrozenBN cost across the trunk: {(dt_bn - dt_none) * 1e3:+.2f} ms "
        f"({'fused/free' if abs(dt_bn - dt_none) < 0.05 * dt_bn else 'NOT free'})"
    )
    m_fold = ResNet(
        blocks=blocks, out_levels=(2, 3, 4, 5), norm="frozen_bn",
        fold_bn=True, dtype=dtype,
    )
    dt_fold, _ = time_trunk(m_fold, "full trunk, fold_bn=true (A/B)")
    print(f"fold_bn recovers: {(dt_bn - dt_fold) * 1e3:+.2f} ms of the BN cost")

    # FPN neck + per-level RPN head on the real model/variables.
    v = {"params": params, **rest}
    feats = jax.jit(
        lambda vv, im: model.apply(vv, im, method="features")
    )(v, imgs)
    feats = jax.device_put(feats)

    def feats_loss(p, im):
        out = model.apply({"params": p, **rest}, im, method="features")
        return sum(jnp.sum(f.astype(jnp.float32) ** 2) for f in out.values())

    # Freeze via the production mask: loop.FREEZE_PREFIXES paths.
    from mx_rcnn_tpu.train.loop import FREEZE_PREFIXES
    from mx_rcnn_tpu.train.optim import frozen_mask

    mask = frozen_mask(params, FREEZE_PREFIXES.get(name, ()))

    def masked(p):
        return jax.tree_util.tree_map(
            lambda x, t: x if t else jax.lax.stop_gradient(x), p, mask
        )

    grad_feats = jax.jit(lambda p, im: jax.grad(
        lambda pp, i: feats_loss(masked(pp), i)
    )(p, im))
    dt_feats = timed(grad_feats, params, args.steps, extra=imgs)
    print(
        f"\n{'features (trunk+FPN neck)':34s} {dt_feats * 1e3:8.2f} ms/step"
        f"  (FPN delta vs trunk: {(dt_feats - dt_bn) * 1e3:+.2f} ms)"
    )

    levels = sorted(feats)
    for lvls in [levels] + [[l] for l in levels]:
        sub = {l: feats[l] for l in lvls}

        def rpn_loss(p, ft):
            out = model.apply({"params": p, **rest}, ft, method="rpn")
            return sum(
                jnp.sum(o.astype(jnp.float32) ** 2)
                for pair in out.values() for o in pair
            )

        grad_rpn = jax.jit(lambda p, ft: jax.grad(rpn_loss)(p, ft))
        dt = timed(grad_rpn, params, args.steps, extra=sub)
        tag = "all levels" if len(lvls) > 1 else f"P{lvls[0]} only"
        print(f"{'rpn head ' + tag:34s} {dt * 1e3:8.2f} ms/step fwd+bwd")


def _infer_breakdown(args, model, params, rest, batch, mcfg) -> None:
    """Ablation timing of forward_inference (the eval path), forward only.

    Stages: backbone features -> +RPN/proposal gen -> +ROIAlign+box head ->
    full inference (softmax, per-class decode, per-class NMS, global
    top-D).  The chain carry is the image tensor (every stage reads it), so
    each scanned step provably depends on the previous one."""
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.detection import forward_inference
    from mx_rcnn_tpu.detection.graph import (
        _pool_rois,
        _postprocess_one,
        _propose_on_features,
    )

    v = {"params": params, **rest}

    def front(imgs, upto: str):
        bt = batch._replace(images=imgs)
        feats = model.apply(v, imgs, method="features")
        if upto == "features":
            s = sum(jnp.sum(f.astype(jnp.float32) ** 2) for f in feats.values())
            return imgs * 0.0 + s
        props = _propose_on_features(model, v, feats, bt)
        if upto == "proposals":
            return imgs * 0.0 + (jnp.sum(props.rois) + jnp.sum(props.scores))
        pooled = _pool_rois(
            mcfg, feats, props.rois, mcfg.rcnn.pooled_size, model.roi_levels
        )
        ps = mcfg.rcnn.pooled_size
        cls_logits, box_deltas = model.apply(
            v, pooled.reshape(-1, ps, ps, pooled.shape[-1]), method="box"
        )
        if upto == "boxhead":
            s = jnp.sum(cls_logits.astype(jnp.float32) ** 2) + jnp.sum(
                box_deltas.astype(jnp.float32) ** 2
            )
            return imgs * 0.0 + s
        raise ValueError(upto)

    def full(imgs):
        dets = forward_inference(model, v, batch._replace(images=imgs))
        return imgs * 0.0 + (jnp.sum(dets.boxes) + jnp.sum(dets.scores))

    b = batch.images.shape[0]
    stages = [
        ("backbone features", lambda im: front(im, "features")),
        ("+rpn + proposal gen", lambda im: front(im, "proposals")),
        ("+roialign + box head", lambda im: front(im, "boxhead")),
        ("full inference (+postprocess)", full),
    ]
    results = []
    for name, fn in stages:
        dt = timed(jax.jit(fn), batch.images, args.steps)
        results.append((name, dt))
        print(
            f"{name:32s} {dt * 1e3:8.2f} ms/batch  "
            f"({b / dt:6.1f} img/s)", flush=True
        )
    print("\ndeltas vs previous stage:")
    prev = None
    for name, dt in results:
        d = dt - (prev if prev is not None else 0.0)
        print(f"{name:32s} +{d * 1e3:7.2f} ms")
        prev = dt

    # Standalone postprocess at eval shapes: R rois x (C-1) classes, NMS per
    # class, global top-D — vmapped over the batch like the real path.
    import numpy as np

    rng = np.random.RandomState(7)
    r = mcfg.rpn.test_post_nms_top_n
    c = mcfg.num_classes
    rois = np.asarray(rng.rand(b, r, 4) * 700, np.float32)
    rois[..., 2:] += 16 + 150 * rng.rand(b, r, 2).astype(np.float32)
    probs = jnp.asarray(rng.dirichlet(np.ones(c), size=(b, r)), jnp.float32)
    deltas = jnp.asarray(
        rng.randn(b, r, 1 if mcfg.rcnn.class_agnostic else c, 4) * 0.1,
        jnp.float32,
    )
    rv = jnp.ones((b, r), bool)
    hw = batch.image_hw

    from mx_rcnn_tpu.detection.graph import _postprocess_one_fused

    for mode, fn in (
        ("per_class", _postprocess_one),
        ("fused", _postprocess_one_fused),
    ):
        def post(pr, fn=fn):
            out = jax.vmap(
                lambda ro, rv_, p, d, hw_: fn(mcfg, ro, rv_, p, d, hw_)
            )(jnp.asarray(rois), rv, pr, deltas, hw)
            return pr * 0.0 + (jnp.sum(out[0]) + jnp.sum(out[1]))

        dt = timed(jax.jit(post), probs, args.steps)
        star = " <- config default" if mcfg.test.nms_mode == mode else ""
        print(
            f"\nstandalone postprocess[{mode}] ({r} rois x {c - 1} classes) "
            f"x{b}: {dt * 1e3:8.2f} ms{star}"
        )


if __name__ == "__main__":
    main()
