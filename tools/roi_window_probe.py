"""Probe: eval-path roi window-class distribution.

Runs forward_proposals at bench eval shapes (random weights — the same
distribution bench.py --eval measures on), then classifies each roi by the
smallest (Ty, Tx) window whose taps it fits under the kernel's origin and
8-alignment rules, per FPN level.  The r4 numbers this produced drive
ops/pallas/roi_align.py::window_classes — re-run it if the proposal
distribution changes (e.g. trained weights, new canvas).

Run from anywhere: the repo path is inserted below (do NOT use
PYTHONPATH=repo — entries there are on sys.path during sitecustomize and
shadow a module the TPU-tunnel registration imports, killing the axon
backend; script-dir insertion happens after site init).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import get_config
from mx_rcnn_tpu.detection import Batch, TwoStageDetector
from mx_rcnn_tpu.detection.graph import forward_proposals, init_detector
from mx_rcnn_tpu.ops.roi_align import fpn_level_assignment

cfg = get_config("r50_fpn_coco")
h, w, b = 800, 1344, 8
model = TwoStageDetector(cfg=cfg.model)
variables = jax.device_put(init_detector(model, jax.random.PRNGKey(0), (h, w)))
rng = np.random.RandomState(0)
g = 32
batch = Batch(
    images=jnp.asarray(rng.randint(0, 256, (b, h, w, 3), dtype=np.uint8)),
    image_hw=jnp.asarray([[float(h), float(w)]] * b, jnp.float32),
    gt_boxes=jnp.zeros((b, g, 4), jnp.float32),
    gt_classes=jnp.zeros((b, g), jnp.int32),
    gt_valid=jnp.zeros((b, g), bool),
)
stats = (cfg.data.pixel_mean, cfg.data.pixel_std)
props = jax.device_get(
    jax.jit(lambda v, bt: forward_proposals(model, v, bt, pixel_stats=stats))(
        variables, batch
    )
)
rois = props.rois.reshape(-1, 4)
valid = props.valid.reshape(-1)
rois = rois[valid]
print(f"{len(rois)} valid rois of {b}x{props.rois.shape[1]}", file=sys.stderr)

# P2-P5 only: detector.roi_levels clamps pooling at 5 (P6 is RPN-only),
# and _prep assigns within the POOLING levels — max_level=6 here would
# count the biggest rois at a scale production never pools them at.
assign = np.asarray(fpn_level_assignment(jnp.asarray(rois), 2, 5, max_extent_cells=38))
scale = 1.0 / (1 << assign)
x1 = rois[:, 0] * scale
y1 = rois[:, 1] * scale
ex = np.maximum(rois[:, 2] * scale - x1, 1.0)
ey = np.maximum(rois[:, 3] * scale - y1, 1.0)
# Same bound as _prep: oy_s = clip(floor(y1)-1, ...); needs y_hi - oy <= T-1.
# Worst case (ignoring map-edge clamps helping): y span floor(y1+ey)+2 - (floor(y1)-1)
y_need = np.floor(y1 + ey) + 2 - (np.floor(y1) - 1) + 1  # cells incl. endpoints
# x: origin clips into the map (as _prep does) then floors to a
# multiple of 8 -> up to +7 slack; an unclamped left-edge origin would
# anchor at -8 and inflate x_need.
ox = (np.clip(np.floor(x1) - 1, 0, None) // 8) * 8
x_need = np.floor(x1 + ex) + 2 - ox + 1

print("extent percentiles (cells): ey", np.percentile(ey, [50, 90, 99]).round(1),
      "ex", np.percentile(ex, [50, 90, 99]).round(1))
print("need percentiles: y", np.percentile(y_need, [50, 90, 99]).round(1),
      "x", np.percentile(x_need, [50, 90, 99]).round(1))
for ty, tx in [(16, 16), (16, 24), (24, 24), (24, 32), (32, 32), (48, 48)]:
    fit = (y_need <= ty) & (x_need <= tx)
    print(f"fits ({ty:2d},{tx:2d}): {fit.mean()*100:5.1f}%")
for lvl in sorted(set(assign)):
    m = assign == lvl
    print(f"level {lvl}: {m.mean()*100:5.1f}% of rois, "
          f"median need y {np.median(y_need[m]):.0f} x {np.median(x_need[m]):.0f}")
