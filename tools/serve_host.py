"""Run one serving-fabric process: a host (fleet + RPC + gossip) or the
pod gateway.

Host mode builds a real FleetRouter (tiny model, random params, hermetic
CPU with fake devices), exports it over the stdlib RPC surface
(serve/rpc.py), and joins the health gossip mesh.  Gateway mode runs a
GatewayRouter over ``--targets`` and exports the SAME RPC surface, so
callers (tools/loadgen.py --gateway, the chaos harness) speak one
protocol to a host or to the whole pod.

Readiness is announced on stdout (parents parse these lines):

    HOST_READY host_id=hostA port=41327 pid=12345
    GATEWAY_READY port=41901 pid=12346

Shutdown: SIGTERM (or POST /rpc/drain) drains the local fleet — stop
admitting, finish accepted work — then exits
``RESUMABLE_EXIT_CODE`` (75), the train/preemption.py convention, so a
supervisor restarts the host and gossip's incarnation numbers retire
the old identity.  While draining, ``/readyz`` answers 503 so balancers
stop sending work before the process goes away.

Usage (2-host fleet + gateway on one machine, all ephemeral ports):

    python tools/serve_host.py --host-id hostA --devices 2 --replicas 2
    python tools/serve_host.py --host-id hostB --devices 2 --replicas 2 \\
        --peers hostA=127.0.0.1:<portA>
    python tools/serve_host.py --gateway \\
        --targets 127.0.0.1:<portA>,127.0.0.1:<portB>
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import threading

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

log = logging.getLogger("serve_host")


def _hermetic_cpu(n_devices: int) -> None:
    """CPU-only jax with ``n_devices`` fake devices.  Must run before the
    first jax import (the XLA flag is read at backend init); prunes any
    non-cpu PJRT plugin the image's sitecustomize registered."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax
    from jax._src import xla_bridge as _xb

    for name in list(_xb._backend_factories):
        if name not in ("cpu", "tpu"):
            _xb._backend_factories.pop(name, None)
    jax.config.update("jax_platforms", "cpu")
    from mx_rcnn_tpu.utils.compile_cache import configure_cpu_cache

    configure_cpu_cache(REPO_ROOT)


def _parse_peers(spec: str) -> dict:
    """``hostA=127.0.0.1:1234,hostB=...`` -> {host_id: addr}."""
    peers = {}
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        host_id, _, addr = item.partition("=")
        if not addr:
            raise ValueError(f"--peers wants host=addr, got {item!r}")
        peers[host_id] = addr
    return peers


def run_host(args: argparse.Namespace) -> int:
    _hermetic_cpu(args.devices)
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.serve import GossipNode, HostRpcServer, build_fleet
    from mx_rcnn_tpu.train.preemption import RESUMABLE_EXIT_CODE

    cfg = get_config(args.config)
    fab = cfg.fabric
    if args.obs_dir:
        obs.configure(args.obs_dir, metrics_port=args.metrics_port)
        obs.install_crash_handler()

    import jax
    from mx_rcnn_tpu.detection import TwoStageDetector, init_detector

    variables = init_detector(
        TwoStageDetector(cfg=cfg.model), jax.random.PRNGKey(args.seed),
        cfg.data.image_size,
    )
    fleet = build_fleet(
        cfg, variables, args.replicas,
        engine_kwargs={"hang_timeout": 300.0, "max_queue": args.max_queue},
        supervisor_poll=0.1,
    )
    print(f"[{args.host_id}] warming {args.replicas} replica(s)...",
          file=sys.stderr, flush=True)
    fleet.start()

    done = threading.Event()
    drain_ok = {"ok": True}

    def on_drain(ok: bool) -> None:
        drain_ok["ok"] = ok
        done.set()

    server = HostRpcServer(
        fleet, args.host_id, port=args.port,
        weights_template=variables, on_drain=on_drain,
    )

    def snapshot() -> dict:
        s = fleet.stats()
        reps = max(1, int(s.get("replicas", 1)))
        return {
            "generation": s.get("generation", 0),
            "load": float(s.get("pending", 0)) / reps,
            "routable": reps,
            "draining": bool(s.get("draining")),
        }

    gossip = GossipNode(
        args.host_id, server.addr, snapshot,
        peers=_parse_peers(args.peers),
        period_s=fab.gossip_period_s,
        suspect_after_s=fab.suspect_after_s,
        dead_after_s=fab.dead_after_s,
    )
    server.gossip = gossip
    server.incarnation = gossip.incarnation
    server.start()
    gossip.start()
    obs.register_status("fleet", fleet.stats)
    obs.register_status("gossip", gossip.snapshot)

    scaler = None
    if args.autoscale:
        from mx_rcnn_tpu.config import CtrlConfig
        from mx_rcnn_tpu.ctrl.autoscale import Autoscaler, ScalePolicy

        # Pod-aggregated signals: this host scales on gossip's view of
        # the whole pod, not just its own queue.
        scaler = Autoscaler(
            fleet, ScalePolicy.from_config(CtrlConfig()),
            pod_view=gossip.aggregate,
        ).start(period_s=1.0)

    def on_sigterm(signum, frame) -> None:
        del signum, frame
        threading.Thread(
            target=lambda: on_drain(fleet.drain(args.drain_timeout)),
            daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, on_sigterm)
    print(
        f"HOST_READY host_id={args.host_id} port={server.port} "
        f"pid={os.getpid()}",
        flush=True,
    )
    done.wait()
    if scaler is not None:
        scaler.stop()
    gossip.close()
    server.close()
    fleet.stop(timeout=60.0)
    print(json.dumps({
        "host_id": args.host_id, "drained": drain_ok["ok"],
        "stats": {
            k: v for k, v in fleet.stats().items() if k != "replica"
        },
    }), flush=True)
    if args.obs_dir:
        obs.close()
    return RESUMABLE_EXIT_CODE


def run_gateway(args: argparse.Namespace) -> int:
    # The gateway holds no model and runs no device code, but jax may be
    # imported transitively — keep it hermetic and CPU-only anyway.
    _hermetic_cpu(1)
    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.serve import GatewayRouter, GossipNode, HostRpcServer

    cfg = get_config(args.config)
    fab = cfg.fabric
    if args.obs_dir:
        obs.configure(args.obs_dir, metrics_port=args.metrics_port)
        obs.install_crash_handler()

    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    gossip = GossipNode(
        "gateway", "", lambda: {"draining": True},
        peers={addr: addr for addr in targets},
        period_s=fab.gossip_period_s,
        suspect_after_s=fab.suspect_after_s,
        dead_after_s=fab.dead_after_s,
    )
    gateway = GatewayRouter(
        targets,
        hedge_after=(
            args.hedge_after if args.hedge_after and args.hedge_after > 0
            else None
        ),
        max_attempts=fab.max_attempts,
        quarantine_failures=fab.quarantine_failures,
        probe_interval_s=fab.probe_interval_s,
        gossip=gossip,
    )
    gateway.start()
    gossip.start()
    server = HostRpcServer(gateway, "gateway", port=args.port,
                           gossip=gossip)
    server.start()
    obs.register_status("gateway", gateway.stats)
    obs.register_status("gossip", gossip.snapshot)

    done = threading.Event()

    def on_sigterm(signum, frame) -> None:
        del signum, frame
        threading.Thread(
            target=lambda: (gateway.drain(args.drain_timeout), done.set()),
            daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, on_sigterm)
    print(f"GATEWAY_READY port={server.port} pid={os.getpid()}",
          flush=True)
    done.wait()
    gossip.close()
    server.close()
    gateway.stop()
    print(json.dumps({"gateway": gateway.stats()}), flush=True)
    if args.obs_dir:
        obs.close()
    return 0


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--gateway", action="store_true",
                   help="run the pod gateway instead of a host fleet")
    p.add_argument("--host-id", default="host0")
    p.add_argument("--config", default="tiny_synthetic")
    p.add_argument("--seed", type=int, default=0,
                   help="weight init seed (hosts in one pod MUST share "
                        "it, or responses differ by host)")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--devices", type=int, default=None,
                   help="fake CPU devices (default: --replicas)")
    p.add_argument("--port", type=int, default=0,
                   help="RPC bind port (0 = ephemeral, announced on "
                        "the READY line)")
    p.add_argument("--peers", default="",
                   help="host mode: hostA=addr,hostB=addr gossip seeds")
    p.add_argument("--targets", default="",
                   help="gateway mode: comma-separated host addrs")
    p.add_argument("--hedge-after", type=float, default=0.0,
                   help="gateway: seconds before a cross-host hedge "
                        "(0 = no hedging)")
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--autoscale", action="store_true",
                   help="host mode: run the autoscaler with "
                        "pod-aggregated gossip signals")
    p.add_argument("--drain-timeout", type=float, default=30.0)
    p.add_argument("--obs-dir", default=None)
    p.add_argument("--metrics-port", type=int, default=0)
    args = p.parse_args(argv)
    if args.devices is None:
        args.devices = max(args.replicas, 1)
    if args.gateway:
        if not args.targets:
            p.error("--gateway requires --targets")
        return run_gateway(args)
    return run_host(args)


if __name__ == "__main__":
    sys.exit(main())
