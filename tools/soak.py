"""Serving soak: diurnal + spike traffic, live chaos, SLO verdicts.

The production rehearsal for the closed control loop (docs/autoscaling.md).
One process runs, concurrently:

* **traffic** — an open-loop arrival schedule composed from the shared
  loadgen profiles (tools/loadgen.py::make_profile): a compressed
  diurnal sine modulating the base rate, with periodic spike bursts
  multiplied on top, so the fleet sees troughs, peaks and steps in a
  single run;
* **the control plane** — an :class:`~mx_rcnn_tpu.ctrl.SLOEngine`
  evaluating availability + latency SLOs on soak-scaled burn windows,
  and an :class:`~mx_rcnn_tpu.ctrl.Autoscaler` resizing the fleet
  between ``--min-replicas`` and ``--max-replicas`` off queue/shed/p99
  pressure;
* **chaos** — a replica kill at mid-run (quarantine -> rebuild under
  load), and optionally (``--data-chaos``) a data-path chaos scenario
  (cache corruption + decode-worker kill) as concurrent subprocesses,
  rehearsing the input service failing while serving burns;
* **adversarial tenancy** (``--tenants``) — the mix becomes one
  open-loop schedule per tenant (flooder/bursty/latency-sensitive —
  same spec as tools/loadgen.py), the fleet enforces per-tenant
  token-bucket quotas (serve/tenancy.py), the SLO engine gains
  per-tenant SLO instances whose burn alerts tighten only the burning
  tenant's quota (QuotaGovernor), and the BENCH record gains a
  per-tenant verdict table: every well-behaved tenant must end HELD
  and the flooder QUOTA-CAPPED for the run to pass;
* **deployment** (``--deploy``) — a fresh validated checkpoint lands
  mid-soak and a :class:`~mx_rcnn_tpu.ctrl.Deployer` stages, gates and
  rolls it live (docs/deployment.md): the BENCH record carries the
  whole shadow -> promote/reject story and the per-SLO verdicts must
  hold THROUGH the roll for the run to pass.

Verdict: the run PASSES only if every SLO held (whole-run error budget
not exhausted) and no accepted request was lost.  Prints
``[soak] SLO VERDICT: HELD`` (or ``VIOLATED``) on stderr and exactly
one ``BENCH_soak`` JSON record as the LAST stdout line, carrying the
per-SLO verdicts, the autoscaler's resize-decision timeline (with the
input signals for every decision) and a per-degrade-level latency
summary.

Two engine modes:

* default — real :func:`~mx_rcnn_tpu.serve.fleet.build_fleet` engines
  (tiny model, hermetic CPU, one fake device per ``--max-replicas``);
* ``--fake-engines`` — a runner-protocol fake with a configurable
  service time, no model build: the shape of the rehearsal in seconds,
  used by tests/test_ctrl.py and the CI ``soak_smoke`` job.

Usage:
    JAX_PLATFORMS=cpu python tools/soak.py --duration 60 --qps 8
    python tools/soak.py --fake-engines --duration 12 --qps 40

(The training-side endurance run lives in tools/train_soak.py.)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.loadgen import (
    _hermetic_cpu,
    _occupancy_summary,
    _percentile,
    make_profile,
    parse_tenant_load_spec,
    tenant_table_string,
)


class _SoakRunner:
    """Runner-protocol fake with a fixed service time (no JAX, no
    model).  Mirrors tests/test_serve.py::FakeRunner — kept separate so
    the tool never imports the test suite."""

    def __init__(self, delay: float, buckets=((64, 64),)):
        self.buckets = sorted(
            (tuple(b) for b in buckets), key=lambda b: b[0] * b[1]
        )
        self.batch_size = 1
        self.delay = delay
        self.generation = 0
        self._warmed = set()

    def levels(self):
        return ("full", "reduced", "proposals")

    def pick_bucket(self, h, w):
        for b in self.buckets:
            if b[0] >= h and b[1] >= w:
                return b
        return self.buckets[-1]

    def smaller_bucket(self, bucket):
        i = self.buckets.index(tuple(bucket))
        return self.buckets[i - 1] if i > 0 else None

    def warmup(self):
        for b in self.buckets:
            for mode in ("full", "reduced", "proposals"):
                self._warmed.add((mode, b))
        return len(self._warmed)

    def swap_weights(self, variables, generation=None):
        gen = self.generation + 1 if generation is None else int(generation)
        self.generation = gen
        return gen

    def run(self, mode, bucket, images):
        import numpy as np

        assert (mode, tuple(bucket)) in self._warmed
        time.sleep(self.delay)
        return [
            {
                "boxes": np.zeros((0, 4), np.float32),
                "scores": np.zeros(0, np.float32),
                "classes": np.zeros(0, np.int32),
                "generation": self.generation,
            }
            for _ in images
        ]


def _build_fake_fleet(args, tenancy=None):
    from mx_rcnn_tpu.serve import FleetRouter, InferenceEngine

    def factory(rid: int) -> InferenceEngine:
        return InferenceEngine(
            _SoakRunner(args.service_time),
            replica_id=rid,
            hang_timeout=60.0,
            max_queue=args.max_queue,
            tenancy=tenancy,
            tenancy_admit=False,  # the router charges the quota
        )

    return FleetRouter(
        factory, args.replicas,
        supervisor_poll=0.05, hedge_after=None,
        tenancy=tenancy,
    )


def _build_real_fleet(args, tenancy=None):
    import jax

    from mx_rcnn_tpu.config import get_config
    from mx_rcnn_tpu.detection import TwoStageDetector, init_detector
    from mx_rcnn_tpu.serve import build_fleet

    cfg = get_config(args.config)
    variables = init_detector(
        TwoStageDetector(cfg=cfg.model), jax.random.PRNGKey(0),
        cfg.data.image_size,
    )
    kwargs = {} if tenancy is None else {"tenancy": tenancy}
    return build_fleet(
        cfg, variables, args.replicas,
        engine_kwargs={"hang_timeout": 300.0, "max_queue": args.max_queue},
        supervisor_poll=0.1,
        hedge_after="auto",
        **kwargs,
    )


def _drop_deploy_candidate(args, ckpt_dir: str) -> None:
    """Land a validated step-1 checkpoint mid-soak.  Runs off the
    arrival loop's thread — a real-model init there would distort the
    latency SLO the run is judged on.  The real-engine candidate is the
    same seed-0 tree the fleet already serves (bitwise parity -> the
    roll itself is the event under test); the fake-engine candidate is
    a toy tree the weight-agnostic runners accept."""
    import numpy as np

    from mx_rcnn_tpu.train import checkpoint

    if args.fake_engines:
        variables = {"w": np.zeros((4,), np.float32)}
    else:
        import jax

        from mx_rcnn_tpu.config import get_config
        from mx_rcnn_tpu.detection import TwoStageDetector, init_detector

        cfg = get_config(args.config)
        variables = init_detector(
            TwoStageDetector(cfg=cfg.model), jax.random.PRNGKey(0),
            cfg.data.image_size,
        )
    checkpoint.save_checkpoint(
        ckpt_dir, {"step": 1, "variables": variables},
        wait=True, manifest=True,
    )


def _deploy_story(deployer, t0: float) -> dict:
    """The shadow -> gate -> promote/reject (-> rollback) story from
    the Deployer's own journal mirror, soak-clock timestamps."""
    keep = ("step", "generation", "reason", "verdict", "mirrored",
            "compared", "mismatched", "from_generation", "to_generation",
            "restored_generation", "slo")
    kinds = [h["kind"] for h in deployer.history]
    return {
        "ckpt_dir": deployer.ckpt_dir,
        "timeline": [
            dict({k: h[k] for k in keep if k in h},
                 kind=h["kind"], t_s=round(h["t"] - t0, 2))
            for h in deployer.history
        ],
        "promoted": "deploy_promote" in kinds,
        "rejected": "deploy_reject" in kinds,
        "rolled_back": "deploy_rollback" in kinds,
        "decided": any(
            k in kinds for k in ("deploy_promote", "deploy_reject")
        ),
    }


def _spawn_data_chaos(root: str) -> list[subprocess.Popen]:
    """Data-path chaos concurrent with the serving soak: the input
    service corrupting cache entries and losing decode workers while
    the fleet is under load.  Each scenario is its own subprocess (the
    chaos harness is self-contained); the soak only demands they PASS."""
    procs = []
    for scenario in ("cache_corrupt", "data_worker_kill"):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(root, "tools", "chaos.py"),
             "--scenario", scenario],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=root,
        ))
    return procs


def run_soak(args: argparse.Namespace) -> dict:
    import numpy as np

    from mx_rcnn_tpu import obs
    from mx_rcnn_tpu.config import CtrlConfig
    from mx_rcnn_tpu.ctrl import (
        Autoscaler,
        ScalePolicy,
        SLOEngine,
        default_slos,
        tenant_slos,
    )
    from mx_rcnn_tpu.serve import (
        Overloaded,
        QuotaExceeded,
        QuotaGovernor,
        ServeError,
        TenancyPolicy,
    )
    from mx_rcnn_tpu.serve.tenancy import parse_table

    obs.configure(args.obs_dir, flush_s=max(args.ctrl_period, 0.5))
    print(f"[soak] obs: run_id={obs.run_id()} dir={obs.out_dir()}",
          file=sys.stderr)

    tenant_specs = getattr(args, "_tenant_specs", None)
    policy = None
    if tenant_specs:
        policy = TenancyPolicy(
            parse_table(tenant_table_string(tenant_specs))
        )
    fleet = (_build_fake_fleet if args.fake_engines
             else _build_real_fleet)(args, tenancy=policy)
    mode = "fake" if args.fake_engines else "real"
    print(f"[soak] starting {args.replicas} {mode} replica(s)...",
          file=sys.stderr)
    fleet.start()
    obs.register_status("fleet", fleet.stats)
    print("[soak] fleet ready", file=sys.stderr)

    # Burn windows scaled to the run so a soak-length incident can trip
    # both windows: minutes-long fast/slow windows would never fire in
    # a CI-sized rehearsal.
    fast_s = max(2.0, args.duration * 0.1)
    slow_s = max(fast_s, args.duration * 0.4)
    ctrl = CtrlConfig(
        availability_target=args.availability_target,
        latency_target=args.latency_target,
        latency_threshold_s=args.latency_threshold,
    )
    slos = default_slos(ctrl)
    governor = None
    if tenant_specs:
        # Per-tenant SLO instances for the WELL-BEHAVED tenants only:
        # the flooder is judged by its quota cap, not an SLO it is
        # expected to blow; its burn must never reach the governor.
        well_behaved = [
            e["name"] for e in tenant_specs if e["role"] != "flooder"
        ]
        slos = slos + tenant_slos(ctrl, well_behaved)
        governor = QuotaGovernor(policy)
    slo_engine = SLOEngine(
        slos, fast_s=fast_s, slow_s=slow_s,
        burn_factor=args.burn_factor,
        on_alert=None if governor is None else governor.on_alert,
    ).start(args.ctrl_period)
    scaler = Autoscaler(
        fleet,
        ScalePolicy(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            load_high=args.load_high,
            load_low=args.load_low,
            down_dwell=args.down_dwell,
            up_cooldown_s=args.up_cooldown,
            down_cooldown_s=args.down_cooldown,
        ),
        p99_window_s=max(fast_s, 5.0),
    ).start(args.ctrl_period)

    deployer = None
    deploy_drop_t: list[float] = []
    if args.deploy:
        import tempfile

        from mx_rcnn_tpu.config import get_config
        from mx_rcnn_tpu.ctrl import build_deployer

        deploy_ckpt = args.deploy_ckpt_dir or tempfile.mkdtemp(
            prefix="soak_deploy_ckpt_"
        )
        # Soak-scaled deploy knobs over cfg.ctrl.deploy: the gate must
        # settle inside one run, and the watch window spans the rest of
        # it so a post-roll burn still triggers rollback before the
        # verdict is read.
        deployer = build_deployer(
            get_config(args.config), fleet,
            ckpt_dir=deploy_ckpt, live_slo=slo_engine,
            poll_s=max(0.3, args.ctrl_period),
            mirror_rate=1.0, min_mirrored=4,
            shadow_window_s=min(8.0, args.duration * 0.25),
            watch_window_s=args.duration,
        ).start(recover=False)
        print(f"[soak] deploy: watching {deploy_ckpt}", file=sys.stderr)

    # Diurnal sine modulated by spike bursts: base * burst-multiplier.
    base = make_profile(
        "sine", args.qps, amplitude=args.amplitude,
        period_s=args.duration / args.cycles,
    )
    burst = make_profile(
        "spike", 1.0, spike_factor=args.spike_factor,
        period_s=args.duration / args.cycles, duty=args.duty,
    )

    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (48, 48, 3), dtype=np.uint8) \
        if not args.fake_engines else np.zeros((48, 48, 3), np.float32)

    lock = threading.Lock()
    by_level: dict[str, list[float]] = {}
    submitted = shed = quota = failed = 0
    pending: list[threading.Thread] = []
    tstats: dict[str, dict] = {
        e["name"]: {"submitted": 0, "shed": 0, "quota": 0, "failed": 0,
                    "lat": []}
        for e in (tenant_specs or [])
    }

    def collect(freq, t_submit: float, tenant: str | None = None) -> None:
        nonlocal quota, failed
        ts = tstats.get(tenant)
        try:
            res = freq.result(timeout=args.deadline + 60.0)
        except QuotaExceeded:
            with lock:
                quota += 1
                if ts is not None:
                    ts["quota"] += 1
            return
        except ServeError:
            with lock:
                failed += 1
                if ts is not None:
                    ts["failed"] += 1
            return
        lat = time.monotonic() - t_submit
        level = res.get("level", "full")
        with lock:
            by_level.setdefault(level, []).append(lat)
            if ts is not None:
                ts["lat"].append(lat)

    chaos_procs: list[subprocess.Popen] = []
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.data_chaos:
        chaos_procs = _spawn_data_chaos(root)
        print(f"[soak] data chaos: {len(chaos_procs)} scenario "
              f"subprocess(es) running", file=sys.stderr)

    killed_rid = None
    t0 = time.monotonic()
    next_at = t0
    deadline_wall = t0 + args.duration

    def chaos_tick(t: float) -> None:
        """The soak's mid-run events, shared by both arrival shapes."""
        nonlocal killed_rid
        if deployer is not None and not deploy_drop_t \
                and t >= args.duration * 0.3:
            deploy_drop_t.append(t)
            threading.Thread(
                target=_drop_deploy_candidate,
                args=(args, deployer.ckpt_dir),
                name="soak-deploy-drop", daemon=True,
            ).start()
            print(f"[soak] deploy: candidate step 1 landing at "
                  f"t={t:.1f}s", file=sys.stderr)
        if args.kill_replica and killed_rid is None \
                and t >= args.duration * 0.4:
            # Kill a currently-routable replica (rids are sparse under
            # autoscaling, so pick from live stats, not range()).  Only
            # with a failover target standing: killing the LAST routable
            # replica can't prove resilience, only loss — if the
            # autoscaler has drained to one, wait for the next tick.
            live = [rep["rid"] for rep in fleet.stats()["replica"]
                    if rep["state"] in ("ready", "degraded")]
            if len(live) >= 2:
                killed_rid = min(live)
                fleet.kill_replica(killed_rid, "soak chaos")
                print(f"[soak] killed replica {killed_rid} at "
                      f"t={t:.1f}s", file=sys.stderr)

    if tenant_specs:
        # One open-loop schedule per tenant (same shape as tools/
        # loadgen.py --tenants): the flooder bouncing off its quota
        # never slows the victims' offered load, and a bursty tenant
        # rides its own spike profile.
        period = args.duration / args.cycles

        def tenant_loop(ent: dict) -> None:
            nonlocal submitted, shed, quota, failed
            name = ent["name"]
            ts = tstats[name]
            rate = make_profile(
                ent["profile"],
                ent["qps"] if ent["qps"]
                else max(args.qps / len(tenant_specs), 0.1),
                amplitude=args.amplitude, period_s=period,
                spike_factor=args.spike_factor, duty=args.duty,
            )
            nxt = t0
            while True:
                now = time.monotonic()
                if now >= deadline_wall:
                    return
                if now < nxt:
                    time.sleep(min(nxt - now, 0.02))
                    continue
                nxt += 1.0 / rate(now - t0)
                try:
                    freq = fleet.submit(
                        img, timeout=args.deadline, tenant=name
                    )
                except QuotaExceeded:
                    with lock:
                        submitted += 1
                        quota += 1
                        ts["submitted"] += 1
                        ts["quota"] += 1
                    continue
                except Overloaded:
                    with lock:
                        submitted += 1
                        shed += 1
                        ts["submitted"] += 1
                        ts["shed"] += 1
                    continue
                except ServeError:
                    with lock:
                        submitted += 1
                        failed += 1
                        ts["submitted"] += 1
                        ts["failed"] += 1
                    continue
                with lock:
                    submitted += 1
                    ts["submitted"] += 1
                th = threading.Thread(
                    target=collect, args=(freq, now, name), daemon=True
                )
                th.start()
                pending.append(th)

        loops = [
            threading.Thread(target=tenant_loop, args=(e,), daemon=True)
            for e in tenant_specs
        ]
        for th in loops:
            th.start()
        while True:
            now = time.monotonic()
            if now >= deadline_wall:
                break
            chaos_tick(now - t0)
            time.sleep(0.05)
        for th in loops:
            th.join(timeout=args.duration + 120.0)
    else:
        while True:
            now = time.monotonic()
            if now >= deadline_wall:
                break
            if now < next_at:
                time.sleep(min(next_at - now, 0.02))
                continue
            t = now - t0
            next_at += 1.0 / (base(t) * burst(t))
            chaos_tick(t)
            try:
                freq = fleet.submit(img, timeout=args.deadline)
            except Overloaded:
                with lock:
                    submitted += 1
                    shed += 1
                continue
            except ServeError:
                with lock:
                    submitted += 1
                    failed += 1
                continue
            with lock:
                submitted += 1
            th = threading.Thread(
                target=collect, args=(freq, now), daemon=True
            )
            th.start()
            pending.append(th)

    print(f"[soak] load window done ({submitted} arrivals); draining...",
          file=sys.stderr)
    for th in pending:
        th.join(timeout=args.deadline + 120.0)
    if deployer is not None:
        deployer.stop()
    scaler.stop()
    slo_engine.stop()   # runs a final observe() so verdicts cover the tail
    stats = fleet.stats()
    fleet.stop(timeout=240.0)

    chaos = None
    if chaos_procs:
        chaos = []
        for p in chaos_procs:
            out, _ = p.communicate(timeout=600)
            last = [ln for ln in out.splitlines() if ln.strip()]
            chaos.append({
                "cmd": p.args[-1],
                "rc": p.returncode,
                "tail": last[-1] if last else "",
            })
            print(f"[soak] data chaos {p.args[-1]}: rc={p.returncode}",
                  file=sys.stderr)

    verdicts = slo_engine.verdicts()
    tenants_rec = None
    if tenant_specs:
        # Per-tenant verdict table: well-behaved tenants must have every
        # tenant-scoped SLO held; the flooder is judged by its cap — a
        # flooder that was never quota-limited means the bucket leaked.
        vds_by_tenant: dict[str, list] = {}
        for v in verdicts:
            if v.get("tenant"):
                vds_by_tenant.setdefault(v["tenant"], []).append(v)
        tenants_rec = {}
        for e in tenant_specs:
            name = e["name"]
            ts = tstats[name]
            lat = sorted(ts["lat"])
            vds = vds_by_tenant.get(name, [])
            slo_held = all(v["held"] for v in vds) if vds else None
            if e["role"] == "flooder":
                verdict = "QUOTA-CAPPED" if ts["quota"] > 0 else "UNCAPPED"
            elif slo_held is not False and ts["failed"] == 0 and lat:
                verdict = "HELD"
            else:
                verdict = "VIOLATED"
            tenants_rec[name] = {
                "role": e["role"],
                "submitted": ts["submitted"],
                "completed": len(lat),
                "shed": ts["shed"],
                "quota": ts["quota"],
                "failed": ts["failed"],
                "p50_s": round(_percentile(lat, 0.50), 4),
                "p99_s": round(_percentile(lat, 0.99), 4),
                "slo_held": slo_held,
                "verdict": verdict,
            }
    completed = sum(len(v) for v in by_level.values())
    latency_by_level = {}
    for level, vals in sorted(by_level.items()):
        vals.sort()
        latency_by_level[level] = {
            "n": len(vals),
            "p50_s": round(_percentile(vals, 0.50), 4),
            "p99_s": round(_percentile(vals, 0.99), 4),
            "max_s": round(vals[-1], 4),
        }
    rec = {
        "bench": "soak",
        "engine_mode": mode,
        "duration_s": args.duration,
        "profile": {
            "base": "sine", "burst": "spike", "qps": args.qps,
            "amplitude": args.amplitude, "cycles": args.cycles,
            "spike_factor": args.spike_factor, "duty": args.duty,
        },
        "replicas_initial": args.replicas,
        "replicas_final": stats["replicas"],
        "added": stats["added"],
        "retired": stats["retired"],
        "submitted": submitted,
        "completed": completed,
        "shed": shed,
        "quota": quota,
        "failed": failed,
        "killed_rid": killed_rid,
        "quarantines": stats["quarantines"],
        "reinstatements": stats["reinstatements"],
        "latency_by_level": latency_by_level,
        # Packing/zero-copy efficiency: batch occupancy across every
        # replica's device calls, plus the shm ring counters when the
        # data plane ran in-process (subprocess chaos counters live in
        # the children's own BENCH lines).
        "occupancy": _occupancy_summary(),
        "shm": {
            name: round(sum(series.values()), 2)
            for name, series in obs.registry().snapshot().items()
            if name.startswith("data_shm_") and series
        },
        # Result-cache counters (serve/result_cache.py); empty when the
        # soak fleet runs cache-off (the default — coalescing would mask
        # the queue pressure the autoscaler story asserts on).
        "cache": {
            name: round(sum(series.values()), 2)
            for name, series in obs.registry().snapshot().items()
            if name.startswith("serve_cache_") and series
        },
        "slo": {
            "fast_s": round(fast_s, 2),
            "slow_s": round(slow_s, 2),
            "burn_factor": args.burn_factor,
            "verdicts": verdicts,
            "burn_alerts": [
                {k: (round(v, 4) if isinstance(v, float) else v)
                 for k, v in a.items()}
                for a in slo_engine.alerts
            ],
        },
        "resize_timeline": [
            {**d, "t": round(d["t"] - t0, 2)}
            for d in scaler.resize_timeline()
        ],
        "tenants": tenants_rec,
        "quota_governor": None if governor is None else [
            {"action": a, "tenant": t} for a, t in governor.actions
        ],
        "data_chaos": chaos,
        "deploy": None if deployer is None else dict(
            _deploy_story(deployer, t0),
            dropped_at_s=(
                round(deploy_drop_t[0], 2) if deploy_drop_t else None
            ),
            generation_final=fleet.generation,
        ),
        "obs": {"run_id": obs.run_id(), "dir": obs.out_dir()},
    }
    obs.close()
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--duration", type=float, default=45.0)
    p.add_argument("--qps", type=float, default=8.0,
                   help="diurnal baseline arrival rate")
    p.add_argument("--amplitude", type=float, default=0.5,
                   help="diurnal swing as a fraction of --qps")
    p.add_argument("--cycles", type=float, default=2.0,
                   help="diurnal cycles across the run")
    p.add_argument("--spike-factor", type=float, default=3.0,
                   help="burst multiplier on the diurnal rate")
    p.add_argument("--duty", type=float, default=0.15,
                   help="fraction of each cycle spent bursting")
    p.add_argument("--replicas", type=int, default=2,
                   help="fleet size at t=0")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--load-high", type=float, default=3.0)
    p.add_argument("--load-low", type=float, default=0.5)
    p.add_argument("--down-dwell", type=int, default=3)
    p.add_argument("--up-cooldown", type=float, default=3.0)
    p.add_argument("--down-cooldown", type=float, default=8.0)
    p.add_argument("--ctrl-period", type=float, default=0.5,
                   help="control-loop evaluation period (seconds)")
    p.add_argument("--availability-target", type=float, default=0.95)
    p.add_argument("--latency-target", type=float, default=0.95)
    p.add_argument("--latency-threshold", type=float, default=30.0,
                   help="latency SLO: good means under this (seconds)")
    p.add_argument("--burn-factor", type=float, default=3.0)
    p.add_argument("--deadline", type=float, default=120.0)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--config", default="tiny_synthetic")
    p.add_argument("--fake-engines", action="store_true",
                   help="runner-protocol fakes instead of real models "
                        "(seconds-scale; used by tests and CI smoke)")
    p.add_argument("--service-time", type=float, default=0.01,
                   help="--fake-engines: per-request service time")
    p.add_argument("--kill-replica", action="store_true", default=True)
    p.add_argument("--no-kill-replica", dest="kill_replica",
                   action="store_false",
                   help="skip the mid-run replica kill")
    p.add_argument("--data-chaos", action="store_true",
                   help="run cache-corruption + decode-worker-kill "
                        "chaos scenarios as concurrent subprocesses")
    p.add_argument("--deploy", action="store_true",
                   help="land a fresh checkpoint mid-soak and run the "
                        "continuous-deployment pipeline (ctrl/deploy.py) "
                        "against the live fleet; the BENCH record gains "
                        "the shadow->promote/reject story")
    p.add_argument("--deploy-ckpt-dir", default=None,
                   help="--deploy: checkpoint dir to land the candidate "
                        "in (default: a temp dir)")
    p.add_argument("--tenants", default="",
                   help="adversarial multi-tenant mix (same spec as "
                        "tools/loadgen.py --tenants): per-tenant "
                        "schedules + serve.tenancy quotas + per-tenant "
                        "SLO verdicts in the BENCH record; the "
                        "role=flooder tenant must end QUOTA-CAPPED and "
                        "every other tenant HELD for the run to pass")
    p.add_argument("--obs-dir", default=None,
                   help="obs journal/spans dir (default: a temp dir)")
    args = p.parse_args(argv)
    if args.tenants:
        try:
            args._tenant_specs = parse_tenant_load_spec(args.tenants)
        except ValueError as e:
            p.error(str(e))
    if args.obs_dir is None:
        import tempfile

        args.obs_dir = tempfile.mkdtemp(prefix="soak_obs_")
    if not args.fake_engines or args.deploy:
        # --deploy needs jax either way: the candidate checkpoint is
        # saved/restored through train/checkpoint.py.  +1 device slot
        # covers the out-of-rotation shadow replica.
        _hermetic_cpu(args.max_replicas + 1)

    rec = run_soak(args)

    held = all(v["held"] for v in rec["slo"]["verdicts"])
    ok = held and rec["failed"] == 0 and rec["completed"] > 0
    if args.data_chaos and rec["data_chaos"] is not None:
        ok = ok and all(c["rc"] == 0 for c in rec["data_chaos"])
    if args.deploy:
        # The deployment must have reached a decision, and the per-SLO
        # verdicts above must hold THROUGH the roll — a promote that
        # burns the budget fails the soak even after rollback.
        ok = ok and rec["deploy"] is not None and rec["deploy"]["decided"]
    if args.tenants and rec["tenants"] is not None:
        # Isolation proof: every well-behaved tenant HELD, and the
        # flooder actually hit its cap (an uncapped flooder means the
        # bucket never bit — the rehearsal proved nothing).
        tnts = rec["tenants"].values()
        ok = ok and all(
            t["verdict"] == "HELD" for t in tnts if t["role"] != "flooder"
        )
        flooders = [t for t in tnts if t["role"] == "flooder"]
        if flooders:
            ok = ok and any(
                t["verdict"] == "QUOTA-CAPPED" for t in flooders
            )
    rec["held"] = held
    rec["pass"] = ok
    print(json.dumps(rec))
    for v in rec["slo"]["verdicts"]:
        print(f"[soak] slo {v['slo']}: budget_remaining="
              f"{v['budget_remaining']:+.4f} worst_burn_fast="
              f"{v['worst_burn_fast']} alerts={v['burn_alerts']} "
              f"held={v['held']}", file=sys.stderr)
    print(f"[soak] fleet resizes: +{rec['added']} -{rec['retired']} "
          f"(final {rec['replicas_final']})", file=sys.stderr)
    if rec.get("tenants"):
        for name, t in rec["tenants"].items():
            print(f"[soak] tenant {name} ({t['role']}): "
                  f"submitted={t['submitted']} completed={t['completed']} "
                  f"shed={t['shed']} quota={t['quota']} "
                  f"failed={t['failed']} p99={t['p99_s']}s "
                  f"verdict={t['verdict']}", file=sys.stderr)
    if rec.get("deploy"):
        d = rec["deploy"]
        story = "promoted" if d["promoted"] else (
            "rejected" if d["rejected"] else "undecided"
        )
        if d["rolled_back"]:
            story += " then rolled back"
        print(f"[soak] deploy: candidate {story}; fleet at generation "
              f"{d['generation_final']}", file=sys.stderr)
    print(f"[soak] SLO VERDICT: {'HELD' if held else 'VIOLATED'}",
          file=sys.stderr)
    if not ok:
        print(f"[soak] FAIL: held={held} failed={rec['failed']} "
              f"completed={rec['completed']}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
