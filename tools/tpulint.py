"""tpulint CLI: prove the train/eval steps are TPU-clean.

Runs both static-analysis layers (mx_rcnn_tpu/analysis/) and writes
``artifacts/tpulint_report.json``:

* layer 1 — AST lint over the jit-traced package source, diffed against
  the committed baseline (``tpulint_baseline.json``); only NEW findings
  fail.
* layer 2 — jaxpr/HLO invariants on the real jitted train/eval/proposal
  steps (f64-free, transfer-guard-clean, trace-deterministic,
  donation-applied, >=99% FLOP attribution, and TPU006: no bf16->f32
  upcast outside the accumulation allowlist in the bf16-mixed train
  step).  No suppressions.

Usage:
  python tools/tpulint.py --check                 # CI gate: exit 1 on any
                                                  # new finding / failed
                                                  # invariant
  python tools/tpulint.py                         # report only, exit 0
  python tools/tpulint.py --ast-only [paths...]   # fast source-only pass
  python tools/tpulint.py --jaxpr-only            # invariants only
  python tools/tpulint.py --write-baseline        # refreeze layer 1
                                                  # (review the diff!)

Runs entirely under JAX_PLATFORMS=cpu — no accelerator needed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# The jaxpr layer jits the tiny train step; pin CPU before jax loads so a
# degraded TPU tunnel can't hang a lint run (same reasoning as
# tests/conftest.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on new findings / failed invariants")
    ap.add_argument("--ast-only", action="store_true")
    ap.add_argument("--jaxpr-only", action="store_true")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current layer-1 findings as the baseline")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO_ROOT, "tpulint_baseline.json"))
    ap.add_argument("--config", default="tiny_synthetic",
                    help="config preset traced by the jaxpr layer")
    ap.add_argument("--out",
                    default=os.path.join(REPO_ROOT, "artifacts",
                                         "tpulint_report.json"))
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files for the AST layer "
                         "(default: all traced modules)")
    args = ap.parse_args(argv)

    from mx_rcnn_tpu.analysis import (
        RULES,
        collect_counts,
        lint_paths,
        load_baseline,
        new_findings,
        run_jaxpr_checks,
        traced_files,
        write_baseline,
    )

    report: dict = {"rules": RULES, "config": args.config}
    failed = False

    if not args.jaxpr_only:
        findings = lint_paths(REPO_ROOT, args.paths or None)
        if args.write_baseline:
            write_baseline(args.baseline, findings)
            print(f"baseline frozen: {len(findings)} findings -> "
                  f"{args.baseline}", file=sys.stderr)
        baseline = load_baseline(args.baseline)
        new = new_findings(findings, baseline)
        report["ast"] = {
            "files_scanned": len(args.paths or traced_files(REPO_ROOT)),
            "total_findings": len(findings),
            "baselined": len(findings) - len(new),
            "new": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "snippet": f.snippet, "fingerprint": f.fingerprint()}
                for f in new
            ],
            "per_rule": {
                rule: sum(1 for f in findings if f.rule == rule)
                for rule in sorted(RULES)
            },
            "fingerprints": collect_counts(findings),
        }
        for f in new:
            print(f"NEW {f.format()}", file=sys.stderr)
        if new:
            failed = True
            print(f"tpulint: {len(new)} new AST finding(s) beyond baseline",
                  file=sys.stderr)
        else:
            print(f"tpulint: AST layer clean "
                  f"({len(findings)} baselined finding(s))", file=sys.stderr)

    if not args.ast_only:
        results = run_jaxpr_checks(args.config)
        report["jaxpr"] = [r.as_dict() for r in results]
        for r in results:
            mark = "PASS" if r.ok else "FAIL"
            print(f"{mark} {r.name}: {r.detail}", file=sys.stderr)
        if not all(r.ok for r in results):
            failed = True

    report["ok"] = not failed
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}", file=sys.stderr)
    print(json.dumps({"metric": "tpulint_ok", "value": bool(report["ok"])}))
    if args.check and failed:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
