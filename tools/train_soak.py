"""Long-run training soak on real hardware (VERDICT r4 #1).

Trains the flagship R50-FPN at the recipe canvas (800x1344) on synthetic
uint8 data for thousands of optimizer steps — through warmup and two
lr-decay boundaries, with a mid-run stop + checkpoint resume — then
evaluates the final state.  This exercises exactly the paths no short
bench or test touches as one continuous run (the reference's analog is
``MutableModule.fit``'s epoch loop over a real schedule, SURVEY.md §3.7):

- schedule dynamics at scale (warmup -> plateau -> two decays);
- bf16 numerical stability over thousands of optimizer steps;
- the checkpoint-every-N branch of the production train loop;
- loader epoch wraparound under run_length grouping (hundreds of images,
  many epochs);
- resume continuity mid-run (phase B restores phase A's checkpoint and
  fast-forwards the data schedule);
- the train -> eval handoff at recipe resolution.

The dataset is the 81-class synthetic renderer in uint8 form, so the
trained program is bit-for-bit the flagship r50_fpn_coco train step
(same class count, same canvas, same dtype path as real COCO training).
Since r4 the renderer uses the "wheel" palette (all 80 classes visually
distinct); the first r4 soak ran the "classic" ramp, whose color
saturation above class ~8 capped absolute AP at 0.128 by construction.
The gates are "loss decreased substantially", "every logged metric
finite", "lr boundaries visible", and "eval AP clears an
untrained-model floor".

Usage:  python tools/train_soak.py [--steps 3000] [--resume-at 1600]
                             [--images 400] [--workdir runs/soak]
Prints one JSON summary line on stdout; diagnostics on stderr.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_soak_config(steps: int, workdir: str, preset: str = "r50_fpn_coco"):
    from mx_rcnn_tpu.config import ScheduleConfig, get_config

    cfg = get_config(preset)
    # Absolute step schedule (reference_batch=0: no epoch rescale — the
    # soak pins exact boundaries) with warmup and two decays inside the
    # run.  lr still scales by global_batch/16 = 2/16, i.e. base 0.02 ->
    # 0.0025 at per-chip batch 2, the linear-scaling value real training
    # would use on one chip.
    sched = ScheduleConfig(
        base_lr=0.02,
        warmup_steps=500,
        warmup_factor=1.0 / 3.0,
        decay_steps=(steps // 2, steps * 5 // 6),
        factor=0.1,
        total_steps=steps,
        reference_batch=0,
    )
    return dataclasses.replace(
        cfg,
        name=f"{preset}_soak",
        workdir=workdir,
        data=dataclasses.replace(cfg.data, dataset="synthetic", max_gt_boxes=32),
        train=dataclasses.replace(
            cfg.train,
            per_device_batch=2,
            steps_per_call=10,
            schedule=sched,
            checkpoint_every=1000,
            log_every=20,
        ),
    )


def make_roidb(cfg, num_images: int, seed: int = 1):
    from mx_rcnn_tpu.data import SyntheticDataset

    return SyntheticDataset(
        num_images=num_images,
        image_hw=cfg.data.image_size,
        num_classes=cfg.model.num_classes,
        max_objects=8,
        seed=seed,
        dtype="uint8",
        # All 80 classes visually distinct (golden-ratio hue + texture
        # combos) — the classic ramp saturates above class ~8 and capped
        # the r4 soak's absolute AP at 0.128 by renderer design, not by
        # anything the detector did.
        palette="wheel",
    ).roidb()


def make_loader(cfg, roidb, batch_size: int):
    from mx_rcnn_tpu.data import DetectionLoader

    return DetectionLoader(
        roidb,
        cfg.data,
        batch_size=batch_size,
        train=True,
        seed=cfg.train.seed,
        run_length=max(cfg.train.steps_per_call, 1),
        # Mask presets need gt masks rasterized (the synthetic roidb
        # carries octagon polygons) — same wiring train/loop.py uses.
        with_masks=cfg.model.mask.enabled,
    )


def final_eval(cfg, state, roidb):
    """Evaluate the trained state over a slice of the soak set (train-set
    AP: the learning signal the soak gates on).  Mirrors run_eval's body
    with an explicit loader because build_dataset's synthetic default is
    the 5-class float set, not the soak's 81-class uint8 one."""
    import jax

    from mx_rcnn_tpu.data import DetectionLoader
    from mx_rcnn_tpu.detection import TwoStageDetector
    from mx_rcnn_tpu.evalutil import pred_eval
    from mx_rcnn_tpu.parallel.step import eval_variables, make_eval_step

    model = TwoStageDetector(cfg=cfg.model)
    eval_step = make_eval_step(
        model, mesh=None,
        pixel_stats=(cfg.data.pixel_mean, cfg.data.pixel_std),
    )
    variables = jax.device_put(eval_variables(jax.device_get(state)))
    loader = DetectionLoader(
        roidb, cfg.data,
        batch_size=max(cfg.model.test.per_device_batch, 1),
        train=False,
    )
    return pred_eval(
        eval_step, variables, loader, roidb, cfg.model.num_classes,
        style="coco",
    )


def summarize_metrics(path: str, decay_steps) -> dict:
    """Parse metrics.jsonl: finiteness, loss trajectory, lr boundaries."""
    rows = []
    with open(path) as f:
        for line in f:
            rows.append(json.loads(line))
    assert rows, f"{path} is empty"
    nonfinite = []
    for r in rows:
        for k, v in r.items():
            if isinstance(v, float) and not math.isfinite(v):
                nonfinite.append((r.get("step"), k, v))
    by_step = {r["step"]: r for r in rows}

    def lr_near(step, side):
        """lr at the last log <= step (side=before) / first > (after)."""
        steps_logged = sorted(by_step)
        cands = [s for s in steps_logged if (s <= step if side == "before" else s > step)]
        if not cands:
            return None
        s = cands[-1] if side == "before" else cands[0]
        return by_step[s].get("lr")

    losses = [r["loss"] for r in rows if "loss" in r]
    k = max(len(losses) // 20, 1)
    return {
        "logged_rows": len(rows),
        "nonfinite_count": len(nonfinite),
        "nonfinite_first": nonfinite[:3],
        "first_loss": losses[0],
        "mean_first_5pct": sum(losses[:k]) / k,
        "mean_last_5pct": sum(losses[-k:]) / k,
        "last_loss": losses[-1],
        "lr_around_decays": {
            str(d): (lr_near(d, "before"), lr_near(d, "after"))
            for d in decay_steps
        },
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=3000)
    ap.add_argument(
        "--resume-at", type=int, default=1600,
        help="stop phase A here; phase B restores the checkpoint and "
        "continues to --steps (0 disables the resume exercise)",
    )
    ap.add_argument("--images", type=int, default=400)
    ap.add_argument("--workdir", default="runs/soak")
    ap.add_argument("--eval-images", type=int, default=96)
    ap.add_argument(
        "--config", default="r50_fpn_coco",
        help="config preset to soak (e.g. mask_r50_fpn_coco — the mask "
        "branch then trains and checkpoints through the whole run)",
    )
    args = ap.parse_args()
    if args.resume_at and not 0 < args.resume_at < args.steps:
        # Catch this up front: phase A training past the schedule would
        # only surface as an assert after the whole run's chip time.
        ap.error(
            f"--resume-at {args.resume_at} must lie strictly inside "
            f"(0, --steps {args.steps}); pass --resume-at 0 to disable "
            "the resume exercise"
        )

    import jax

    # Same persistent compile cache as bench.py: repeat soak invocations
    # (smoke run, then the real run) skip the multi-minute step compile.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(repo, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 10)

    from mx_rcnn_tpu.cli.common import setup_logging
    from mx_rcnn_tpu.train.loop import train

    setup_logging(True)
    cfg = build_soak_config(args.steps, args.workdir, preset=args.config)
    # A previous run's checkpoints would hijack phase B's resume (it
    # restores the LATEST step — a stale step-3000 checkpoint makes phase
    # B a no-op and the PASS gate score the old params).  Refuse rather
    # than silently wipe.
    from mx_rcnn_tpu.train.checkpoint import latest_step

    ckpt_dir = os.path.join(args.workdir, cfg.name, "ckpt")
    stale = latest_step(ckpt_dir)
    if stale is not None:
        raise SystemExit(
            f"{ckpt_dir} already holds a run (latest step {stale}); delete "
            "it or pass a fresh --workdir — phase B's resume would restore "
            "it instead of this run's phase A"
        )
    global_batch = cfg.train.per_device_batch  # single chip
    t0 = time.perf_counter()
    print(
        f"rendering {args.images} synthetic {cfg.data.image_size} uint8 "
        f"images ({cfg.model.num_classes} classes)...",
        file=sys.stderr,
    )
    roidb = make_roidb(cfg, args.images)
    print(f"rendered in {time.perf_counter() - t0:.0f}s", file=sys.stderr)

    epochs = args.steps * global_batch / args.images
    print(
        f"soak: {args.steps} steps x batch {global_batch} over "
        f"{args.images} images = {epochs:.1f} epochs; decays at "
        f"{cfg.train.schedule.decay_steps}, resume exercise at "
        f"{args.resume_at}, checkpoints every "
        f"{cfg.train.checkpoint_every}",
        file=sys.stderr,
    )

    t_train0 = time.perf_counter()
    if args.resume_at:
        train(
            cfg, total_steps=args.resume_at, workdir=args.workdir,
            loader=make_loader(cfg, roidb, global_batch),
        )
        print(
            f"phase A done at step {args.resume_at} "
            f"({time.perf_counter() - t_train0:.0f}s); resuming...",
            file=sys.stderr,
        )
    state = train(
        cfg, total_steps=args.steps, workdir=args.workdir, resume=True,
        loader=make_loader(cfg, roidb, global_batch),
    )
    t_train = time.perf_counter() - t_train0
    assert int(jax.device_get(state.step)) == args.steps

    metrics = final_eval(cfg, state, roidb[: args.eval_images])
    summary = summarize_metrics(
        os.path.join(args.workdir, cfg.name, "metrics.jsonl"),
        cfg.train.schedule.decay_steps,
    )
    ckpts = sorted(
        os.listdir(os.path.join(args.workdir, cfg.name, "ckpt"))
    )
    out = {
        "steps": args.steps,
        "resume_at": args.resume_at,
        "images": args.images,
        "epochs": round(epochs, 1),
        "train_seconds": round(t_train, 1),
        "img_per_sec": round(args.steps * global_batch / t_train, 2),
        "checkpoints": ckpts,
        "eval": {k: round(float(v), 4) for k, v in metrics.items()},
        **summary,
    }
    print(json.dumps(out))
    # Loss gate against the FIRST logged loss, not the first-5% mean: the
    # steepest descent happens inside the first log window (r4 run: 2.11
    # at step 10, ~1.0 by step 150), so a windowed-mean ratio understates
    # a perfectly healthy curve.  AP floor: see the inline rationale on
    # the gate below (untrained is < 0.001).
    ok = (
        summary["nonfinite_count"] == 0
        and summary["mean_last_5pct"] < 0.6 * summary["first_loss"]
        # Wheel-palette floor: the r4b run read AP 0.556 (classic-ramp
        # runs read 0.128 — renderer-capped); 0.25 catches a real
        # learning regression without pinning a chaotic synthetic value.
        and metrics.get("AP", 0.0) > 0.25
        # Mask presets must also gate the mask head: a segm regression to
        # zero with a healthy box head would otherwise still PASS.  Floor
        # is below the r4b run's 0.2573 by the same margin logic as box.
        and (
            not cfg.model.mask.enabled
            or metrics.get("segm/AP", 0.0) > 0.12
        )
    )
    print(f"SOAK {'PASS' if ok else 'FAIL'}", file=sys.stderr)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
