#!/usr/bin/env python
"""Entry point — see mx_rcnn_tpu/cli/train_cli.py (reference: train driver)."""
from mx_rcnn_tpu.cli.train_cli import main

if __name__ == "__main__":
    main()
