#!/usr/bin/env python
"""Entry point — see mx_rcnn_tpu/cli/alternate_cli.py (reference: train_alternate driver)."""
from mx_rcnn_tpu.cli.alternate_cli import main

if __name__ == "__main__":
    main()
